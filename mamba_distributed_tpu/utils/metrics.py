"""Metrics logging: reference-text-format log + structured jsonl.

``log.txt`` carries exactly the reference's 3-field lines
(``"{step} train {loss:.6f}"`` / ``"{step} val {loss:.4f}"``,
/root/reference/train.py:124,150,240) so its plot tooling (plot.ipynb)
parses ours unchanged.  ``metrics.jsonl`` carries the structured record
SURVEY.md §5 calls for — step, loss, lr, grad norm, step time,
tokens/sec, MFU — one JSON object per line, machine-parseable.  The
console line shows both worlds (the reference printed step/loss/lr/
norm/dt/tok-sec, train.py:237-239; MFU is new).
"""

from __future__ import annotations

import os

from mamba_distributed_tpu.obs.histogram import StreamingHistogram
from mamba_distributed_tpu.obs.tracer import append_jsonl


class MetricsLogger:
    def __init__(self, log_dir: str, master_process: bool = True,
                 filename: str = "log.txt",
                 jsonl_filename: str = "metrics.jsonl"):
        self.master = master_process
        self.log_file = None
        self.jsonl_file = None
        # truncation (reference train.py:122) is deferred to the first write
        # so a checkpoint resume can preserve the pre-crash history
        self._truncate_pending = True
        if master_process:
            os.makedirs(log_dir, exist_ok=True)
            self.log_file = os.path.join(log_dir, filename)
            self.jsonl_file = os.path.join(log_dir, jsonl_filename)

    def preserve_history(self) -> None:
        """Keep the existing log files (called on checkpoint resume)."""
        self._truncate_pending = False

    def _append(self, line: str, record: dict | None = None) -> None:
        if self.log_file:
            mode = "w" if self._truncate_pending else "a"
            if self._truncate_pending:
                # truncate BOTH files together so a record-less first write
                # can never leave a previous run's jsonl to interleave with
                open(self.jsonl_file, "w").close()
            self._truncate_pending = False
            with open(self.log_file, mode) as f:
                f.write(line + "\n")
            if record is not None:
                append_jsonl(self.jsonl_file, record)

    def train_step(self, step: int, loss: float, lr: float, grad_norm: float,
                   dt_s: float, tokens_per_sec: float, mfu: float,
                   mfu_hw: float | None = None) -> None:
        """``mfu`` is the model-FLOPs convention (the judged one);
        ``mfu_hw`` additionally counts the chunked algorithm's extra
        arithmetic (utils/flops.py module docstring)."""
        if not self.master:
            return
        print(
            f"step {step:5d} | loss: {loss:.6f} | lr {lr:.4e} | "
            f"norm: {grad_norm:.4f} | dt: {dt_s * 1000:.2f}ms | "
            f"tok/sec: {tokens_per_sec:.2f} | mfu: {mfu * 100:.1f}%"
        )
        record = {
            "step": step, "kind": "train", "loss": round(loss, 6),
            "lr": lr, "grad_norm": round(grad_norm, 4),
            "step_ms": round(dt_s * 1000, 2),
            "tokens_per_sec": round(tokens_per_sec, 1),
            "mfu": round(mfu, 4),
        }
        if mfu_hw is not None:
            record["mfu_hw"] = round(mfu_hw, 4)
        self._append(f"{step} train {loss:.6f}", record)

    def val(self, step: int, loss: float) -> None:
        if not self.master:
            return
        print(f"validation loss: {loss:.4f}")
        self._append(
            f"{step} val {loss:.4f}",
            {"step": step, "kind": "val", "loss": round(loss, 4)},
        )


def emit_bench_record(record: dict, json_path: str | None = None) -> None:
    """Print a bench record as one JSON line and, when ``json_path`` is
    given, write the same line there — the machine-readable perf-
    trajectory artifact (BENCH_SERVING.json collects these).  Shared by
    scripts/bench_serving.py and scripts/bench_decode.py so the two
    artifacts can never drift in format."""
    import json

    line = json.dumps(record)
    print(line, flush=True)
    if json_path:
        with open(json_path, "w") as f:
            f.write(line + "\n")


class ServingMetrics:
    """Serving-engine counters: queue depth, slot occupancy, throughput.

    The engine (serving/engine.py) calls ``record_prefill`` once per
    admission and ``record_tick`` once per compiled decode tick;
    ``summary()`` rolls everything up for bench output
    (scripts/bench_serving.py).  With ``jsonl_path`` set, every tick also
    appends one structured record — same one-JSON-object-per-line format
    as MetricsLogger's metrics.jsonl, tagged ``"kind": "serving_tick"``.

    Decode is weight-bandwidth-bound, so ``mean_slot_occupancy`` is the
    throughput model: each tick reads the full weights once regardless of
    how many slots are live, and every occupied slot rides that same read
    — batch-fill is (nearly) free aggregate tokens/sec (docs/SERVING.md).

    Per-request latency (the metrics that matter under real traffic:
    queue-wait, time-to-first-token, inter-token latency) aggregates in
    three streaming bounded-bucket histograms (obs/histogram.py) — p50/
    p95/p99 with fixed memory, no samples stored — rolled up under
    ``summary()["latency"]``.  The engine stamps the request lifecycle
    and calls ``record_queue_wait``/``record_ttft``/``record_itl``;
    ``record_request`` additionally appends one ``"kind": "request"``
    jsonl record per finished request when ``jsonl_path`` is set.

    ``replica`` (the data-parallel serving fabric, serving/router.py)
    stamps every serving_tick/request record with the owning replica's
    id, so one shared jsonl stream splits back into per-replica tables
    (scripts/obs_report.py renders queue depth, occupancy, and
    free-page gauges per replica).

    Goodput: every tick record also carries ``useful_tokens`` /
    ``wasted_token_lanes`` / ``goodput_tokens_per_sec`` /
    ``serving_mfu`` — raw tok/s with the static-shape waste (empty
    slot lanes, chunk padding) made visible, and a host-computed MFU
    from the analytic FLOPs rates the engine installs via
    ``configure_goodput`` (utils/flops.py "model" convention; no
    device counters).  ``summary()["goodput"]`` is the roll-up.
    """

    def __init__(self, capacity: int, jsonl_path: str | None = None,
                 replica: int | None = None):
        self.capacity = capacity
        self.jsonl_path = jsonl_path
        self.replica = replica
        self.ticks = 0
        self.decode_tokens = 0
        self.decode_time_s = 0.0
        self.prefills = 0
        self.prefill_tokens = 0
        self.prefill_time_s = 0.0
        # chunked prefill (serving/prefill.py): per-chunk dispatch counters
        # + the per-step prefill stall (host time the engine spends on
        # prefill work between two ticks — what chunking exists to bound)
        self.prefill_chunks = 0
        self.prefill_chunk_tokens = 0
        self.prefill_chunk_time_s = 0.0
        self.prefill_stall_s = 0.0
        self.prefill_stall_ms = StreamingHistogram()
        self._occupied_sum = 0
        self._queue_depth_sum = 0
        self.peak_queue_depth = 0
        # hybrid paged-KV gauges (serving/engine.py): last-seen pool
        # occupancy + cumulative allocator churn; None/0 until a hybrid
        # engine reports them
        self.kv_pages_used: int | None = None
        self.kv_pages_capacity: int | None = None
        self.kv_page_allocs = 0
        self.kv_page_frees = 0
        self.peak_kv_pages_used = 0
        self.finished_requests = 0
        # goodput accounting (serving/engine.py passes the lane counts):
        # useful tokens vs token lanes actually computed (padded slots +
        # chunk padding), plus host-computed serving MFU from the
        # analytic FLOPs rates configure_goodput() installs
        self.useful_tokens = 0
        self.computed_token_lanes = 0
        self._goodput_window_s = 0.0
        self._goodput_flops = 0.0
        self._fpt_decode: float | None = None
        self._fpt_prefill: float | None = None
        self._peak_flops: float | None = None
        self.queue_wait_ms = StreamingHistogram()
        self.ttft_ms = StreamingHistogram()
        self.itl_ms = StreamingHistogram()
        # prefix-state cache (serving/prefix_cache.py): the engine calls
        # configure_prefix_cache() when the cache is on, unlocking the
        # summary()["prefix_cache"] section — hit-rate, saved prefill
        # tokens, and the TTFT split hit-vs-miss (the cache's headline)
        self._prefix_cache_on = False
        self.prefix_full_hits = 0
        self.prefix_partial_hits = 0
        self.prefix_misses = 0
        self.prefix_saved_tokens = 0
        self.prefix_ttft_hit_ms = StreamingHistogram()
        self.prefix_ttft_miss_ms = StreamingHistogram()
        # quantized serving (ops/quant.py; docs/SERVING.md "Quantized
        # serving"): the engine calls configure_memory() when either
        # weight or KV quantization is on, unlocking summary()["memory"]
        # — resident weight bytes, page-pool bytes and the dtype pair —
        # and the greedy-token-disagreement counter the divergence-
        # sentinel-backed parity checker (ops/quant.assert_stream_close)
        # bumps when a quantized stream drifts from its reference
        self._memory_on = False
        self.weight_bytes: int | None = None
        self.page_pool_bytes: int | None = None
        self.weight_dtype: str | None = None
        self.kv_dtype: str | None = None
        self.greedy_token_disagreements = 0
        # speculative decoding (serving/spec_decode.py): the engine
        # calls configure_speculation() when cfg.spec_tokens > 0,
        # unlocking summary()["speculation"] — draft/accept counters,
        # the per-tick acceptance-rate histogram and the headline
        # accepted-tokens-per-tick (committed tokens per full-model
        # launch; > 1 is the bandwidth win).  Off by default so K=0
        # summaries/records stay byte-stable.
        self._spec_on = False
        self.spec_tokens_cfg: int | None = None
        self.spec_drafter: str | None = None
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.spec_stream_ticks = 0  # Σ live streams over verify ticks
        self.spec_accept_rate = StreamingHistogram(lo=1e-2, hi=200.0)
        # occupancy-adaptive compacted ticks (serving/engine.py;
        # docs/SERVING.md "Occupancy-adaptive ticks"): the engine calls
        # configure_compaction() when cfg.tick_compaction is on,
        # unlocking summary()["compaction"] — per-width tick histogram,
        # distinct compiled bucket widths ("recompiles": each width is
        # one gather/tick/scatter trace trio), and the token lanes the
        # narrower launches saved vs static capacity.  Off by default
        # so compaction-off records/summaries stay byte-stable.
        self._compaction_on = False
        self.compaction_ticks = 0  # ticks that ran NARROWER than capacity
        self.compaction_hist: dict[int, int] = {}  # lane width -> ticks
        self.compaction_lanes_saved = 0
        # 3-D serving mesh pipeline axis (parallel/mesh.serving_mesh;
        # docs/SERVING.md "3-D serving mesh"): the engine calls
        # configure_pipeline() when serving_stage_shards > 1,
        # unlocking summary()["pipeline"] — stage width, how many
        # ticks ran the explicit microbatched clock, and the
        # warmup/drain bubble lanes those schedules idled (billed
        # into goodput's wasted_token_lanes).  Off by default so
        # stage=1 records/summaries stay byte-stable.
        self._pipeline_on = False
        self.stage_shards_cfg: int | None = None
        self.pipeline_ticks = 0  # ticks that ran the explicit clock
        self.pipeline_bubble_lanes = 0
        self._pipeline_slot_lanes = 0  # Σ slot_lanes on those ticks
        # multi-tenant LoRA serving (serving/adapters.py): the engine
        # calls configure_adapters() when cfg.lora_max_adapters > 0,
        # unlocking summary()["adapters"] — registry/cache shape,
        # cache hit/miss/eviction totals and the per-tick distinct-
        # adapter gauge.  Off by default so LoRA-less summaries and
        # records stay byte-stable.
        self._adapters_on = False
        self.lora_max_adapters: int | None = None
        self.lora_rank: int | None = None
        self.lora_cache_slots: int | None = None
        self.adapters_resident: int = 0
        self.adapter_cache_hits = 0
        self.adapter_cache_misses = 0
        self.adapter_cache_evictions = 0
        self.peak_adapters_live = 0
        self._adapters_live_sum = 0
        self._adapter_ticks = 0
        # priority preemptions (serving/engine.py swap-out/resume)
        self.preemptions = 0
        # online per-tenant adapter tuning plane (serving/tuning/ plus
        # the engine's fairness quota and mid-stream hot swaps): the
        # owner calls configure_tuning() when any of those features is
        # live — tenant_max_slots > 0 at engine construction, or
        # lazily on the first hot swap / tune job — unlocking
        # summary()["tuning"].  Off by default so tuning-less
        # summaries and records stay byte-stable.
        self._tuning_on = False
        self.tenant_quota_stalls = 0
        self.adapter_hot_swaps = 0
        self.tune_jobs_submitted = 0
        self.tune_jobs_completed = 0
        self.tune_jobs_failed = 0
        self.tune_train_steps = 0
        self.tune_deploys = 0
        self.tune_yields = 0
        self.tune_step_ms = StreamingHistogram()
        self.tune_last_loss: float | None = None
        # durable sessions (serving/sessions/store.py): the engine
        # calls configure_sessions() when a session store is attached,
        # unlocking summary()["sessions"] — park/resume/expire totals,
        # the per-resume restore latency histogram and the last-seen
        # tier gauges (host entries/bytes vs disk entries/bytes).  Off
        # by default so store-less summaries/records stay byte-stable.
        self._sessions_on = False
        self.session_parks = 0
        self.session_resumes = 0
        self.session_expires = 0
        self.session_resume_ms = StreamingHistogram()
        self.sessions_parked_host: int | None = None
        self.sessions_parked_disk: int | None = None
        self.sessions_bytes_host: int | None = None
        self.sessions_bytes_disk: int | None = None
        # admission-control load shedding (serving/autoscale/
        # admission.py): the owner calls configure_admission() when an
        # AdmissionController is installed, unlocking
        # summary()["admission"] — total sheds split by reason.  Off by
        # default so admission-less summaries stay byte-stable.
        self._admission_on = False
        self.sheds = 0
        self.sheds_cap = 0
        self.sheds_deadline = 0
        # disaggregated prefill/decode handoffs (docs/SERVING.md
        # "Disaggregated tiers"): migrations OUT of this engine (a
        # prefill replica exporting its finished carry) vs IN (a
        # decode replica restoring one), with the per-handoff host
        # latency (packaging + restore dispatch)
        self.migrations_out = 0
        self.migrations_in = 0
        self.migration_ms = StreamingHistogram()
        # XLA compile watchdog (obs/watchdog.py): the engine calls
        # configure_compile() when a watchdog is attached, unlocking
        # summary()["compile"] and the per-tick `compiles`/`compile_ms`
        # stamps.  Off by default so watchdog-less records/summaries
        # stay byte-stable.
        self._compile_on = False
        self.compiles = 0
        self.compile_ms_total = 0.0
        # same deferred-truncation contract as MetricsLogger/SpanTracer:
        # a reused path starts fresh on the first write unless
        # preserve_history() ran, so two runs can never interleave
        self._truncate_pending = True

    def preserve_history(self) -> None:
        """Keep an existing jsonl stream (append instead of truncating)."""
        self._truncate_pending = False

    def configure_goodput(self, flops_per_decode_token: float,
                          flops_per_prefill_token: float,
                          peak_flops: float) -> None:
        """Install the analytic FLOPs rates (utils/flops.py, "model"
        convention — no device counters involved) that turn each tick's
        useful-token counts into a host-computed ``serving_mfu``.  The
        engine calls this once at construction; unconfigured metrics
        still emit the goodput token fields with ``serving_mfu=None``."""
        self._fpt_decode = flops_per_decode_token
        self._fpt_prefill = flops_per_prefill_token
        self._peak_flops = peak_flops

    def _write_jsonl(self, record: dict) -> None:
        append_jsonl(self.jsonl_path, record, truncate=self._truncate_pending)
        self._truncate_pending = False

    def record_prefill(self, prompt_tokens: int, dt_s: float) -> None:
        """``dt_s`` is host dispatch time: prefill runs async and the next
        tick's token fetch absorbs device completion (serving/engine.py),
        so on an async backend the derived ``prefill_tokens_per_sec`` is
        a dispatch rate — an upper bound on device prefill throughput,
        not a measurement of it."""
        self.prefills += 1
        self.prefill_tokens += prompt_tokens
        self.prefill_time_s += dt_s

    def record_prefill_chunk(self, chunk_tokens: int, dt_s: float) -> None:
        """One chunked-prefill step (serving/prefill.py): ``chunk_tokens``
        of prompt dispatched in ``dt_s`` host seconds.  The whole prompt
        still gets one ``record_prefill`` at completion, so
        ``prefill_tokens_per_sec`` keeps its meaning; the chunk counters
        give the chunk-level dispatch throughput."""
        self.prefill_chunks += 1
        self.prefill_chunk_tokens += chunk_tokens
        self.prefill_chunk_time_s += dt_s

    def record_prefill_stall(self, dt_s: float) -> None:
        """Host seconds one engine step spent on prefill work (admissions
        + chunk budget) before its tick — the stall chunking bounds."""
        self.prefill_stall_s += dt_s
        self.prefill_stall_ms.record(dt_s * 1000)

    # -------------------------------------------- prefix cache + preemption

    def configure_prefix_cache(self) -> None:
        """Mark the prefix-state cache live (engine construction):
        ``summary()`` gains its ``prefix_cache`` section."""
        self._prefix_cache_on = True

    def record_prefix_lookup(self, kind: str | None,
                             saved_tokens: int = 0) -> None:
        """One admission-time cache lookup: ``kind`` is "full" (prefill
        skipped outright), "partial" (seeded at a chunk boundary) or
        None (miss); ``saved_tokens`` the prompt tokens the hit's
        snapshot covers — prefill work NOT recomputed."""
        if kind == "full":
            self.prefix_full_hits += 1
        elif kind == "partial":
            self.prefix_partial_hits += 1
        else:
            self.prefix_misses += 1
        self.prefix_saved_tokens += saved_tokens

    def record_prefix_ttft(self, dt_s: float, hit: bool) -> None:
        """TTFT of a finished-prefill request, split by cache outcome —
        the delta between the two histograms is what the cache buys."""
        (self.prefix_ttft_hit_ms if hit
         else self.prefix_ttft_miss_ms).record(dt_s * 1000)

    def record_preemption(self) -> None:
        """One priority swap-out (serving/engine._preempt)."""
        self.preemptions += 1

    # ------------------------------------------------- compacted ticks

    def configure_compaction(self) -> None:
        """Mark occupancy-adaptive tick compaction live (engine
        construction): ``summary()`` gains its ``compaction`` block and
        tick records their ``compaction_width`` stamp."""
        self._compaction_on = True

    # ------------------------------------------------ speculative decoding

    def configure_speculation(self, spec_tokens: int, drafter: str) -> None:
        """Mark speculative decoding live (engine construction):
        ``summary()`` gains its ``speculation`` section and tick
        records their ``spec_drafted``/``spec_accepted`` stamps."""
        self._spec_on = True
        self.spec_tokens_cfg = spec_tokens
        self.spec_drafter = drafter

    # ---------------------------------------------- multi-tenant LoRA

    def configure_adapters(self, max_adapters: int, rank: int,
                           cache_slots: int) -> None:
        """Mark multi-tenant LoRA serving live (engine construction):
        ``summary()`` gains its ``adapters`` section and tick records
        their adapter-cache stamps."""
        self._adapters_on = True
        self.lora_max_adapters = int(max_adapters)
        self.lora_rank = int(rank)
        self.lora_cache_slots = int(cache_slots)

    # ------------------------------------------- online adapter tuning

    def configure_tuning(self) -> None:
        """Mark the online-tuning plane live: ``summary()`` gains its
        ``tuning`` section and tick records their quota-stall /
        hot-swap stamps.  Idempotent; the ``record_*`` methods below
        call it lazily, so the section appears exactly when the first
        tuning-plane event happens (byte-stable until then)."""
        self._tuning_on = True

    def record_quota_stall(self) -> None:
        """One admission deferred by the per-tenant fairness quota
        (serving/scheduler.TenantQuotaExceeded — requeued, not shed)."""
        self.configure_tuning()
        self.tenant_quota_stalls += 1

    def record_hot_swap(self) -> None:
        """One live stream switched adapter versions mid-flight
        (serving/engine.hot_swap_adapter)."""
        self.configure_tuning()
        self.adapter_hot_swaps += 1

    def record_tune_job(self, state: str,
                        job: dict | None = None) -> None:
        """One tune-job lifecycle transition: ``state`` is "submitted",
        "completed" or "failed" (serving/tuning/jobs.py).  ``job`` is
        the job's status dict; with a jsonl stream configured it lands
        as one ``"kind": "tune_job"`` record per transition (the
        docs/OBSERVABILITY.md event schema)."""
        self.configure_tuning()
        if state == "submitted":
            self.tune_jobs_submitted += 1
        elif state == "completed":
            self.tune_jobs_completed += 1
        else:
            self.tune_jobs_failed += 1
        if self.jsonl_path and job is not None:
            rec = {"kind": "tune_job", **job}
            if self.replica is not None:
                rec.setdefault("replica", self.replica)
            self._write_jsonl(rec)

    def record_tune_step(self, dt_ms: float,
                         loss: float | None = None) -> None:
        """One masked LoRA train step on a trainer-role replica:
        host wall ms and (when finite) the step's mean loss."""
        self.configure_tuning()
        self.tune_train_steps += 1
        self.tune_step_ms.record(dt_ms)
        if loss is not None:
            self.tune_last_loss = float(loss)

    def record_tune_deploy(self) -> None:
        """One converged job's ``name@v(N+1)`` hot-registered
        fabric-wide (serving/tuning/jobs.py deploy)."""
        self.configure_tuning()
        self.tune_deploys += 1

    def record_tune_yield(self) -> None:
        """One training slice skipped because serving pressure (SLO
        breach / queue depth) reclaimed the lane."""
        self.configure_tuning()
        self.tune_yields += 1

    # --------------------------------------------------- quantized serving

    def configure_memory(self, weight_bytes: int, page_pool_bytes: int,
                         weight_dtype: str, kv_dtype: str) -> None:
        """Install the resident-bytes gauges (engine construction, only
        when quantization is on — ``summary()["memory"]`` stays None and
        tick records byte-stable otherwise)."""
        self._memory_on = True
        self.weight_bytes = int(weight_bytes)
        self.page_pool_bytes = int(page_pool_bytes)
        self.weight_dtype = weight_dtype
        self.kv_dtype = kv_dtype

    # ------------------------------------------- pipeline (3-D mesh)

    def configure_pipeline(self, stage_shards: int) -> None:
        """Mark the serving mesh's pipeline ``stage`` axis live (engine
        construction, only at ``serving_stage_shards > 1``):
        ``summary()`` gains its ``pipeline`` section and tick records
        their ``stage_shards``/``bubble_lanes`` stamps."""
        self._pipeline_on = True
        self.stage_shards_cfg = int(stage_shards)

    # ----------------------------------------------- compile watchdog

    def configure_compile(self) -> None:
        """Mark the XLA compile watchdog live (engine construction):
        ``summary()`` gains its ``compile`` block and tick records
        their ``compiles``/``compile_ms`` stamps."""
        self._compile_on = True

    def record_greedy_disagreement(self, n: int = 1) -> None:
        """``n`` greedy tokens on which a quantized stream disagreed
        with its reference (fed by ops/quant.assert_stream_close — the
        divergence sentinels keep the flight-recorder side)."""
        self.greedy_token_disagreements += n

    def record_migration_out(self) -> None:
        """One prefill-complete carry exported to another replica
        (serving/engine._migrate_ready on a prefill-tier engine)."""
        self.migrations_out += 1

    def record_migration_in(self, dt_ms: float) -> None:
        """One migration artifact restored into a slot here
        (serving/engine._resume); ``dt_ms`` is the handoff's host
        latency — source-side packaging + this restore's dispatch."""
        self.migrations_in += 1
        self.migration_ms.record(dt_ms)

    # ---------------------------------------------------- durable sessions

    def configure_sessions(self) -> None:
        """Mark the durable session store live (engine construction):
        ``summary()`` gains its ``sessions`` section and tick records
        their session stamps (docs/SERVING.md "Durable sessions")."""
        self._sessions_on = True

    def record_session_park(self) -> None:
        """One live stream serialized into the session store (explicit
        ``park()`` or the admission valve's pressure park)."""
        self.session_parks += 1

    def record_session_resume(self, dt_ms: float) -> None:
        """One parked session restored into a slot here; ``dt_ms`` is
        the restore's host latency (store read + decode + dispatch)."""
        self.session_resumes += 1
        self.session_resume_ms.record(dt_ms)

    def record_session_expire(self, n: int = 1) -> None:
        """``n`` parked sessions reaped by the TTL sweeper."""
        self.session_expires += n

    # ------------------------------------------------- admission shedding

    def configure_admission(self) -> None:
        """Mark admission control live (AdmissionController
        construction): ``summary()`` gains its ``admission`` section
        (docs/SERVING.md "Elastic fabric")."""
        self._admission_on = True

    def record_shed(self, reason: str) -> None:
        """One request shed at the front door; ``reason`` is the
        ``AdmissionRejected`` reason ("queue_cap" | "queue_deadline")."""
        self.sheds += 1
        if reason == "queue_cap":
            self.sheds_cap += 1
        else:
            self.sheds_deadline += 1

    # ------------------------------------------------- per-request latency

    def record_queue_wait(self, dt_s: float) -> None:
        """Submit -> slot granted (admission)."""
        self.queue_wait_ms.record(dt_s * 1000)

    def record_ttft(self, dt_s: float) -> None:
        """Submit -> first generated token on the host."""
        self.ttft_ms.record(dt_s * 1000)

    def record_itl(self, dt_s: float, n: int = 1) -> None:
        """``n`` inter-token gaps of ``dt_s`` each (tokens that arrive in
        one tick share the tick's per-token average — the host can't see
        finer than its own sync points)."""
        self.itl_ms.record(dt_s * 1000, n)

    def record_request(self, record: dict) -> None:
        """One finished request: count it and append its jsonl record
        (``"kind": "request"``) when a stream is configured."""
        self.finished_requests += 1
        if self.jsonl_path:
            rec = {"kind": "request", **record}
            if self.replica is not None:
                rec.setdefault("replica", self.replica)
            self._write_jsonl(rec)

    def record_tick(
        self, occupied: int, queue_depth: int, tokens_emitted: int,
        dt_s: float, prefill_stall_ms: float = 0.0,
        prefill_chunk_tokens: int = 0, prefill_chunk_ms: float = 0.0,
        prefill_real_tokens: int = 0,
        prefill_oneshot_tokens: int = 0, prefill_oneshot_lanes: int = 0,
        slot_lanes: int = 0,
        traces: list | None = None,
        model_shards: int | None = None,
        stage_shards: int | None = None,
        bubble_lanes: int | None = None,
        preemptions: int = 0,
        migrations_out: int = 0,
        migrations_in: int = 0,
        tenant_quota_stalls: int = 0,
        adapter_hot_swaps: int = 0,
        prefix_hits: int | None = None,
        prefix_misses: int | None = None,
        prefix_saved_tokens: int | None = None,
        prefix_cache_entries: int | None = None,
        prefix_cache_bytes: int | None = None,
        kv_pages_used: int | None = None,
        kv_pages_capacity: int | None = None,
        kv_page_allocs: int = 0, kv_page_frees: int = 0,
        quantized: dict | None = None,
        weight_bytes: int | None = None,
        page_pool_bytes: int | None = None,
        spec_drafted: int | None = None,
        spec_accepted: int | None = None,
        spec_streams: int | None = None,
        compaction_width: int | None = None,
        adapters_resident: int | None = None,
        adapter_cache_hits: int = 0,
        adapter_cache_misses: int = 0,
        adapter_cache_evictions: int = 0,
        adapters_live: int = 0,
        sessions_parked_host: int | None = None,
        sessions_parked_disk: int | None = None,
        sessions_bytes_host: int | None = None,
        sessions_bytes_disk: int | None = None,
        session_parks: int = 0,
        session_resumes: int = 0,
        session_expires: int = 0,
        compiles: int | None = None,
        compile_ms: float = 0.0,
    ) -> None:
        """``prefill_stall_ms`` is the host time spent on prefill work
        since the PREVIOUS tick record (an engine step whose slots are
        all still mid-prefill runs no tick, so its work rolls into the
        next tick's record — the jsonl stream never drops any);
        ``prefill_chunk_tokens``/``prefill_chunk_ms`` are the chunked-
        prefill tokens dispatched in that window and their dispatch
        time, ``prefill_real_tokens`` the non-pad subset (the chunk-
        padding half of the goodput waste accounting);
        ``prefill_oneshot_tokens``/``prefill_oneshot_lanes`` the same
        real-vs-computed pair for UNCHUNKED admissions in the window
        (real prompt tokens vs the pow2-padded bucket lanes the
        one-shot prefill ran), so goodput/MFU stay comparable across
        the chunking threshold.  ``slot_lanes``
        is the token lanes the compiled tick computed (capacity x
        sub-steps — live or not, the static shape runs them all); with
        the emitted/real counts it yields the per-tick goodput fields:
        ``useful_tokens``, ``wasted_token_lanes``,
        ``goodput_tokens_per_sec`` (useful work over the tick + its
        prefill window) and ``serving_mfu`` (analytic FLOPs of the
        useful tokens over peak — see ``configure_goodput``).
        ``traces`` is the live request trace-id set, stamped into the
        record so host-side attribution can apportion ``tick_ms`` and
        FLOPs across resident requests (obs/context.py).
        ``model_shards`` (tensor-parallel serving engines, i.e. > 1)
        stamps the mesh's model-axis width on the record so per-tick
        rates are attributable to their weight layout; None (the
        replicated default) leaves the record unchanged.
        ``stage_shards`` (3-D pipelined serving engines, i.e. > 1)
        stamps the mesh's stage-axis width the same way, and
        ``bubble_lanes`` bills the explicit microbatched schedule's
        warmup/drain ramp — full-depth lane equivalents the pipeline
        idled this tick, 0 on GSPMD-fallback ticks — into the goodput
        lane count, so ``wasted_token_lanes`` is honest about the
        bubble; None (stage=1) leaves records byte-stable.
        ``prefix_hits``/``prefix_misses``/``prefix_saved_tokens`` are
        the prefix-state cache's window counters and
        ``prefix_cache_entries``/``prefix_cache_bytes`` its occupancy
        gauges — stamped only by cache-enabled engines (None leaves
        the record byte-stable), all host-side.  ``preemptions``
        counts priority swap-outs in the window (stamped only when
        nonzero).
        ``migrations_out``/``migrations_in`` count disaggregated-tier
        handoffs exported/restored in the window (stamped only when
        nonzero; docs/SERVING.md "Disaggregated tiers").
        ``kv_pages_used``/``kv_pages_capacity`` (hybrid paged-KV
        engines) gauge the page pool at this tick, with
        ``kv_page_allocs``/``kv_page_frees`` the allocator churn in the
        window — rendered by scripts/obs_report.py.
        ``quantized`` (int8 serving only — None keeps records
        byte-stable) is the ``{"weights": dtype, "kv": dtype}`` stamp,
        with ``weight_bytes``/``page_pool_bytes`` the resident-bytes
        gauges behind the capacity story (docs/SERVING.md "Quantized
        serving")."""
        self.ticks += 1
        self.decode_tokens += tokens_emitted
        self.decode_time_s += dt_s
        self._occupied_sum += occupied
        self._queue_depth_sum += queue_depth
        self.peak_queue_depth = max(self.peak_queue_depth, queue_depth)
        # --- goodput: useful tokens vs computed lanes over the window
        # (the tick plus the prefill work attributed to it)
        window_s = dt_s + prefill_stall_ms / 1000.0
        useful = (tokens_emitted + prefill_real_tokens
                  + prefill_oneshot_tokens)
        lanes = (slot_lanes + prefill_chunk_tokens
                 + prefill_oneshot_lanes + (bubble_lanes or 0))
        self.useful_tokens += useful
        self.computed_token_lanes += lanes
        self._goodput_window_s += window_s
        mfu = None
        if self._fpt_decode is not None and self._peak_flops and window_s > 0:
            flops = (tokens_emitted * self._fpt_decode
                     + (prefill_real_tokens + prefill_oneshot_tokens)
                     * self._fpt_prefill)
            self._goodput_flops += flops
            mfu = flops / (window_s * self._peak_flops)
        record = {
            "kind": "serving_tick", "tick": self.ticks,
            "occupied": occupied, "capacity": self.capacity,
            **({} if self.replica is None else {"replica": self.replica}),
            "queue_depth": queue_depth,
            "tokens_emitted": tokens_emitted,
            "tick_ms": round(dt_s * 1000, 3),
            "prefill_stall_ms": round(prefill_stall_ms, 3),
            "prefill_chunk_tokens": prefill_chunk_tokens,
            "prefill_chunk_ms": round(prefill_chunk_ms, 3),
            "prefill_oneshot_tokens": prefill_oneshot_tokens,
            "useful_tokens": useful,
            "wasted_token_lanes": max(lanes - useful, 0),
            "goodput_tokens_per_sec": (
                round(useful / window_s, 1) if window_s > 0 else None
            ),
            "serving_mfu": None if mfu is None else round(mfu, 6),
        }
        if traces is not None:
            record["traces"] = list(traces)
        if model_shards is not None:
            record["model_shards"] = model_shards
        if stage_shards is not None:
            # pipeline-axis stamps (only at stage > 1 — 2-D engines'
            # records stay byte-stable): the stage width and this
            # tick's bubble bill (0 when GSPMD ran the layer scan
            # without the explicit microbatch clock)
            record["stage_shards"] = stage_shards
            record["bubble_lanes"] = bubble_lanes or 0
            if bubble_lanes:
                self.pipeline_ticks += 1
                self.pipeline_bubble_lanes += bubble_lanes
                self._pipeline_slot_lanes += slot_lanes
        if preemptions:
            record["preemptions"] = preemptions
        if tenant_quota_stalls:
            # fairness-quota deferrals in the window (stamped only when
            # nonzero — quota-off engines' records stay byte-stable;
            # the cumulative total rides record_quota_stall)
            record["tenant_quota_stalls"] = tenant_quota_stalls
        if adapter_hot_swaps:
            # mid-stream adapter version swaps in the window (stamped
            # only when nonzero — swap-free records stay byte-stable)
            record["adapter_hot_swaps"] = adapter_hot_swaps
        if migrations_out:
            # disaggregated-tier handoffs in the window (stamped only
            # when live, so non-disagg streams stay byte-stable)
            record["migrations_out"] = migrations_out
        if migrations_in:
            record["migrations_in"] = migrations_in
        if prefix_hits is not None:
            record.update({
                "prefix_hits": prefix_hits,
                "prefix_misses": prefix_misses,
                "prefix_saved_tokens": prefix_saved_tokens,
                "prefix_cache_entries": prefix_cache_entries,
                "prefix_cache_bytes": prefix_cache_bytes,
            })
        if kv_pages_used is not None:
            self.kv_pages_used = kv_pages_used
            self.kv_pages_capacity = kv_pages_capacity
            self.kv_page_allocs += kv_page_allocs
            self.kv_page_frees += kv_page_frees
            self.peak_kv_pages_used = max(
                self.peak_kv_pages_used, kv_pages_used
            )
            record.update({
                "kv_pages_used": kv_pages_used,
                "kv_pages_capacity": kv_pages_capacity,
                "kv_page_allocs": kv_page_allocs,
                "kv_page_frees": kv_page_frees,
            })
        if quantized is not None:
            record["quantized"] = quantized
            record["weight_bytes"] = weight_bytes
            if page_pool_bytes is not None:
                record["page_pool_bytes"] = page_pool_bytes
        if spec_drafted is not None:
            # speculative-decoding window counters (stamped only when
            # speculation is on — K=0 records stay byte-stable): draft
            # lanes fed to the verify step and how many verified.  The
            # acceptance-rate histogram records the window's rate in
            # PERCENT (0-100) so the geometric buckets resolve it.
            self.spec_drafted += spec_drafted
            self.spec_accepted += spec_accepted or 0
            if spec_drafted:
                self.spec_accept_rate.record(
                    100.0 * (spec_accepted or 0) / spec_drafted
                )
            self.spec_stream_ticks += spec_streams or 0
            record["spec_drafted"] = spec_drafted
            record["spec_accepted"] = spec_accepted
            record["spec_streams"] = spec_streams
        if adapters_resident is not None:
            # multi-tenant LoRA gauges (stamped only when LoRA serving
            # is on — records stay byte-stable otherwise): cache
            # residency, this window's hit/miss/eviction churn, and
            # how many DISTINCT adapters this tick's one launch mixed
            self.adapters_resident = adapters_resident
            self.adapter_cache_hits += adapter_cache_hits
            self.adapter_cache_misses += adapter_cache_misses
            self.adapter_cache_evictions += adapter_cache_evictions
            self.peak_adapters_live = max(self.peak_adapters_live,
                                          adapters_live)
            self._adapters_live_sum += adapters_live
            self._adapter_ticks += 1
            record.update({
                "adapters_resident": adapters_resident,
                "adapter_cache_hits": adapter_cache_hits,
                "adapter_cache_misses": adapter_cache_misses,
                "adapter_cache_evictions": adapter_cache_evictions,
                "adapters_live": adapters_live,
            })
        if sessions_parked_host is not None:
            # durable-session gauges (stamped only when a session
            # store is attached — store-less records stay byte-stable):
            # tier occupancy at this tick plus this window's
            # park/resume/expire churn
            self.sessions_parked_host = sessions_parked_host
            self.sessions_parked_disk = sessions_parked_disk
            self.sessions_bytes_host = sessions_bytes_host
            self.sessions_bytes_disk = sessions_bytes_disk
            record.update({
                "sessions_parked_host": sessions_parked_host,
                "sessions_parked_disk": sessions_parked_disk,
                "sessions_bytes_host": sessions_bytes_host,
                "sessions_bytes_disk": sessions_bytes_disk,
                "session_parks": session_parks,
                "session_resumes": session_resumes,
                "session_expires": session_expires,
            })
        if compiles is not None:
            # compile-watchdog window counters (stamped only when a
            # watchdog is attached — records stay byte-stable
            # otherwise): XLA backend compiles observed since the
            # previous tick record and their wall ms.  A steady-state
            # engine stamps 0/0.0; anything persistently nonzero is
            # recompile thrash the watchdog's window event names.
            self.compiles += compiles
            self.compile_ms_total += compile_ms
            record["compiles"] = compiles
            record["compile_ms"] = round(compile_ms, 3)
        if compaction_width is not None:
            # occupancy-adaptive compaction stamp (only when the engine
            # has compaction on — records stay byte-stable otherwise):
            # the lane width this tick's launch computed.  slot_lanes
            # above is already billed at that width, so the goodput
            # fields price the compacted launch, not static capacity;
            # lanes_saved is the delta a full-width launch would have
            # burned on the same tick.
            record["compaction_width"] = compaction_width
            self.compaction_hist[compaction_width] = (
                self.compaction_hist.get(compaction_width, 0) + 1
            )
            if compaction_width < self.capacity:
                self.compaction_ticks += 1
                self.compaction_lanes_saved += (
                    slot_lanes * self.capacity // compaction_width
                    - slot_lanes
                )
        if self.jsonl_path:
            self._write_jsonl(record)

    def summary(self) -> dict:
        return {
            "ticks": self.ticks,
            "decode_tokens": self.decode_tokens,
            "decode_tokens_per_sec": (
                round(self.decode_tokens / self.decode_time_s, 1)
                if self.decode_time_s else None
            ),
            "mean_tick_ms": (
                round(self.decode_time_s / self.ticks * 1000, 3)
                if self.ticks else None
            ),
            "mean_slot_occupancy": (
                round(self._occupied_sum / (self.ticks * self.capacity), 4)
                if self.ticks else 0.0
            ),
            "mean_queue_depth": (
                round(self._queue_depth_sum / self.ticks, 2) if self.ticks else 0.0
            ),
            "peak_queue_depth": self.peak_queue_depth,
            "prefills": self.prefills,
            "prefill_tokens": self.prefill_tokens,
            "prefill_time_s": round(self.prefill_time_s, 4),
            "prefill_tokens_per_sec": (
                round(self.prefill_tokens / self.prefill_time_s, 1)
                if self.prefill_time_s else None
            ),
            "prefill_chunks": self.prefill_chunks,
            "prefill_chunk_tokens": self.prefill_chunk_tokens,
            "prefill_chunk_tokens_per_sec": (
                round(self.prefill_chunk_tokens / self.prefill_chunk_time_s, 1)
                if self.prefill_chunk_time_s else None
            ),
            "prefill_stall_s": round(self.prefill_stall_s, 4),
            "prefill_stall_ms": self.prefill_stall_ms.summary(),
            "finished_requests": self.finished_requests,
            "preemptions": self.preemptions,
            "migrations": {
                "out": self.migrations_out,
                "in": self.migrations_in,
                "migration_ms": self.migration_ms.summary(),
            },
            "prefix_cache": (None if not self._prefix_cache_on else {
                "full_hits": self.prefix_full_hits,
                "partial_hits": self.prefix_partial_hits,
                "misses": self.prefix_misses,
                "hit_rate": (
                    round((self.prefix_full_hits + self.prefix_partial_hits)
                          / (self.prefix_full_hits + self.prefix_partial_hits
                             + self.prefix_misses), 4)
                    if (self.prefix_full_hits + self.prefix_partial_hits
                        + self.prefix_misses) else None
                ),
                "saved_prefill_tokens": self.prefix_saved_tokens,
                "ttft_hit_ms": self.prefix_ttft_hit_ms.summary(),
                "ttft_miss_ms": self.prefix_ttft_miss_ms.summary(),
            }),
            "goodput": {
                "useful_tokens": self.useful_tokens,
                "wasted_token_lanes": max(
                    self.computed_token_lanes - self.useful_tokens, 0
                ),
                "useful_fraction": (
                    round(self.useful_tokens / self.computed_token_lanes, 4)
                    if self.computed_token_lanes else None
                ),
                "goodput_tokens_per_sec": (
                    round(self.useful_tokens / self._goodput_window_s, 1)
                    if self._goodput_window_s else None
                ),
                "serving_mfu": (
                    round(self._goodput_flops
                          / (self._goodput_window_s * self._peak_flops), 6)
                    if (self._peak_flops and self._goodput_window_s
                        and self._fpt_decode is not None) else None
                ),
            },
            "compaction": (None if not self._compaction_on else {
                "ticks_compacted": self.compaction_ticks,
                # one gather/tick/scatter trace trio per distinct
                # NARROW width ever used (full-width launches reuse
                # the pre-existing tick trace)
                "recompiles": sum(1 for w in self.compaction_hist
                                  if w < self.capacity),
                "bucket_histogram": {
                    str(w): n
                    for w, n in sorted(self.compaction_hist.items())
                },
                "lanes_saved": self.compaction_lanes_saved,
            }),
            "pipeline": (None if not self._pipeline_on else {
                "stage_shards": self.stage_shards_cfg,
                # ticks that ran the explicit microbatched clock (the
                # rest fell back to the GSPMD layer scan — same bits,
                # no ramp) and the ramp's cumulative idle lanes
                "pipelined_ticks": self.pipeline_ticks,
                "bubble_lanes": self.pipeline_bubble_lanes,
                "bubble_fraction": (
                    round(self.pipeline_bubble_lanes
                          / (self.pipeline_bubble_lanes
                             + self._pipeline_slot_lanes), 4)
                    if (self.pipeline_bubble_lanes
                        + self._pipeline_slot_lanes) else None
                ),
            }),
            "speculation": (None if not self._spec_on else {
                "spec_tokens": self.spec_tokens_cfg,
                "drafter": self.spec_drafter,
                "drafted": self.spec_drafted,
                "accepted": self.spec_accepted,
                "acceptance_rate": (
                    round(self.spec_accepted / self.spec_drafted, 4)
                    if self.spec_drafted else None
                ),
                "acceptance_rate_pct_hist":
                    self.spec_accept_rate.summary(),
                # committed tokens per STREAM per full-model launch —
                # the launches-per-token headline inverted (> 1.5 is
                # the bench gate on the repetitive-suffix workload; a
                # non-speculative tick is pinned at exactly 1.0)
                "accepted_tokens_per_tick": (
                    round(self.decode_tokens / self.spec_stream_ticks, 2)
                    if self.spec_stream_ticks else None
                ),
            }),
            "adapters": (None if not self._adapters_on else {
                "max_adapters": self.lora_max_adapters,
                "rank": self.lora_rank,
                "cache_slots": self.lora_cache_slots,
                "resident": self.adapters_resident,
                "cache_hits": self.adapter_cache_hits,
                "cache_misses": self.adapter_cache_misses,
                "cache_evictions": self.adapter_cache_evictions,
                "peak_live": self.peak_adapters_live,
                "mean_live": (
                    round(self._adapters_live_sum
                          / self._adapter_ticks, 2)
                    if self._adapter_ticks else None
                ),
            }),
            "tuning": (None if not self._tuning_on else {
                "quota_stalls": self.tenant_quota_stalls,
                "hot_swaps": self.adapter_hot_swaps,
                "jobs_submitted": self.tune_jobs_submitted,
                "jobs_completed": self.tune_jobs_completed,
                "jobs_failed": self.tune_jobs_failed,
                "train_steps": self.tune_train_steps,
                "deploys": self.tune_deploys,
                "yields": self.tune_yields,
                "step_ms": self.tune_step_ms.summary(),
                "last_loss": self.tune_last_loss,
            }),
            "admission": (None if not self._admission_on else {
                "sheds": self.sheds,
                "sheds_cap": self.sheds_cap,
                "sheds_deadline": self.sheds_deadline,
            }),
            "sessions": (None if not self._sessions_on else {
                "parked_host": self.sessions_parked_host,
                "parked_disk": self.sessions_parked_disk,
                "bytes_host": self.sessions_bytes_host,
                "bytes_disk": self.sessions_bytes_disk,
                "parks": self.session_parks,
                "resumes": self.session_resumes,
                "expires": self.session_expires,
                "resume_ms": self.session_resume_ms.summary(),
            }),
            "memory": (None if not self._memory_on else {
                "weight_bytes": self.weight_bytes,
                "page_pool_bytes": self.page_pool_bytes,
                "weight_dtype": self.weight_dtype,
                "kv_dtype": self.kv_dtype,
                "greedy_token_disagreements":
                    self.greedy_token_disagreements,
            }),
            "kv_pages": (
                None if self.kv_pages_used is None else {
                    "used": self.kv_pages_used,
                    "capacity": self.kv_pages_capacity,
                    "peak_used": self.peak_kv_pages_used,
                    "allocs": self.kv_page_allocs,
                    "frees": self.kv_page_frees,
                }
            ),
            "compile": (None if not self._compile_on else {
                "compiles": self.compiles,
                "compile_ms": round(self.compile_ms_total, 3),
            }),
            "latency": {
                "queue_wait_ms": self.queue_wait_ms.summary(),
                "ttft_ms": self.ttft_ms.summary(),
                "itl_ms": self.itl_ms.summary(),
            },
        }

    def histogram_dicts(self) -> dict:
        """Full sparse bucket forms of the latency histograms
        (``StreamingHistogram.to_dict``) — what the Prometheus
        exposition needs (``summary()`` carries only the p50/p95/p99
        roll-ups; bucket lines need the counts).  Shipped next to the
        summary in the worker ``summary`` RPC payload."""
        out = {
            "queue_wait_ms": self.queue_wait_ms.to_dict(),
            "ttft_ms": self.ttft_ms.to_dict(),
            "itl_ms": self.itl_ms.to_dict(),
        }
        if self._tuning_on:
            # gated like summary()["tuning"]: a tuning-less fabric's
            # exposition stays byte-identical (no empty histogram)
            out["tune_step_ms"] = self.tune_step_ms.to_dict()
        return out
