"""Metrics logging, reference-text-format compatible.

The log file carries exactly the reference's 3-field lines
(``"{step} train {loss:.6f}"`` / ``"{step} val {loss:.4f}"``,
/root/reference/train.py:124,150,240) so its plot tooling (plot.ipynb)
parses ours unchanged; the console line additionally carries lr, grad
norm, step time, tokens/sec, and MFU (the reference printed the first
four, train.py:237-239; MFU is new).
"""

from __future__ import annotations

import os


class MetricsLogger:
    def __init__(self, log_dir: str, master_process: bool = True,
                 filename: str = "log.txt"):
        self.master = master_process
        self.log_file = None
        # truncation (reference train.py:122) is deferred to the first write
        # so a checkpoint resume can preserve the pre-crash history
        self._truncate_pending = True
        if master_process:
            os.makedirs(log_dir, exist_ok=True)
            self.log_file = os.path.join(log_dir, filename)

    def preserve_history(self) -> None:
        """Keep the existing log file (called on checkpoint resume)."""
        self._truncate_pending = False

    def _append(self, line: str) -> None:
        if self.log_file:
            mode = "w" if self._truncate_pending else "a"
            self._truncate_pending = False
            with open(self.log_file, mode) as f:
                f.write(line + "\n")

    def train_step(self, step: int, loss: float, lr: float, grad_norm: float,
                   dt_s: float, tokens_per_sec: float, mfu: float) -> None:
        if not self.master:
            return
        print(
            f"step {step:5d} | loss: {loss:.6f} | lr {lr:.4e} | "
            f"norm: {grad_norm:.4f} | dt: {dt_s * 1000:.2f}ms | "
            f"tok/sec: {tokens_per_sec:.2f} | mfu: {mfu * 100:.1f}%"
        )
        self._append(f"{step} train {loss:.6f}")

    def val(self, step: int, loss: float) -> None:
        if not self.master:
            return
        print(f"validation loss: {loss:.4f}")
        self._append(f"{step} val {loss:.4f}")
