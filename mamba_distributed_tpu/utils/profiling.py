"""Profiling: jax.profiler trace capture + simple step timing.

The reference's only instrumentation is wall-clock around the step with a
device synchronize (/root/reference/train.py:129,228-238).  Here:
  * ``trace(dir)`` — context manager capturing a TensorBoard-viewable
    XLA trace (kernel timeline, HBM traffic) via ``jax.profiler``;
  * ``StepTimer`` — host-side step timing with a forced device sync
    (transfer of a scalar), the moral equivalent of cuda.synchronize.
"""

from __future__ import annotations

import contextlib
import time
import warnings

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a profiler trace for the enclosed steps.

    View with TensorBoard's profile plugin pointed at ``log_dir``.
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock timing with an explicit sync on a device scalar.

    ``block_until_ready`` is a no-op on some experimental platforms, so
    syncing is done by fetching the scalar's value.
    """

    def __init__(self):
        self._t0 = None

    def start(self) -> None:
        self._t0 = time.time()

    def stop(self, sync_scalar=None) -> float:
        if sync_scalar is not None:
            float(jax.device_get(sync_scalar))
        if self._t0 is None:
            # a timing bug must not kill the run it is measuring
            warnings.warn(
                "StepTimer.stop() called without start(); returning 0.0",
                RuntimeWarning, stacklevel=2,
            )
            return 0.0
        return time.time() - self._t0
