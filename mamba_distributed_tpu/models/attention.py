"""Causal self-attention with RoPE (hybrid Jamba-style layers).

Functional equivalent of ``mamba_ssm.modules.mha.MHA`` as used by hybrid
configs via ``attn_layer_idx``/``attn_cfg`` (mamba-ssm 2.2.2; the reference
never enables it — SURVEY.md §2.3 — but BASELINE.json config 5 requires it).

GQA layout: packed qkv projection, ``num_heads`` query heads sharing
``num_kv_heads`` KV heads; rotary embedding on the leading ``rotary_dim``
of each head.  Under sequence parallelism the score/value contraction runs
as ring attention over the mesh's ``seq`` axis (parallel/ring_attention.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from mamba_distributed_tpu.config import ModelConfig
from mamba_distributed_tpu.models.common import init_linear, linear


def _attn_dims(cfg: ModelConfig):
    nh = cfg.effective_attn_num_heads
    nkv = cfg.effective_attn_num_kv_heads
    hd = cfg.effective_attn_head_dim
    # -1 => full head dim; 0 => no rotary (mamba_ssm's rotary_emb_dim)
    rot = hd if cfg.attn_rotary_dim < 0 else cfg.attn_rotary_dim
    return nh, nkv, hd, rot


def init_attention_params(key: jax.Array, cfg: ModelConfig) -> dict:
    nh, nkv, hd, _ = _attn_dims(cfg)
    k_qkv, k_out = jax.random.split(key)
    params = {
        "wqkv": init_linear(k_qkv, cfg.d_model, (nh + 2 * nkv) * hd, cfg.proj_bias),
        "out_proj": init_linear(k_out, nh * hd, cfg.d_model, cfg.proj_bias),
    }
    if cfg.rescale_prenorm_residual:
        n_residuals = 2 if cfg.d_intermediate > 0 else 1
        params["out_proj"]["kernel"] = params["out_proj"]["kernel"] / math.sqrt(
            n_residuals * cfg.n_layer
        )
    return params


def rope_angles(positions: jax.Array, rotary_dim: int, theta: float) -> jax.Array:
    """(t,) int positions -> (t, rotary_dim/2) angles."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, rotary_dim, 2, dtype=jnp.float32) / rotary_dim)
    )
    return positions.astype(jnp.float32)[:, None] * inv_freq[None, :]


def apply_rope(x: jax.Array, angles: jax.Array) -> jax.Array:
    """Rotate the leading ``2*angles.shape[-1]`` channels of each head.

    x (b, t, h, hd); angles (t, rot/2).  Rotate-half (GPT-NeoX,
    non-interleaved) convention on the rotary slice — pairs are
    (x[i], x[i + rot/2]) — matching the flash-attn RotaryEmbedding
    default (``interleaved=False``) that mamba_ssm's MHA layers use, so
    hybrid checkpoints import with bit-compatible attention semantics.
    The tail past the rotary slice passes through.
    """
    rot = 2 * angles.shape[-1]
    xr, x_pass = x[..., :rot], x[..., rot:]
    xf = xr.astype(jnp.float32)
    x1, x2 = xf[..., : rot // 2], xf[..., rot // 2 :]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    o1 = x1 * cos - x2 * sin
    o2 = x1 * sin + x2 * cos
    out = jnp.concatenate([o1, o2], axis=-1).astype(x.dtype)
    return jnp.concatenate([out, x_pass], axis=-1) if x_pass.size else out


def _split_qkv(qkv: jax.Array, cfg: ModelConfig):
    nh, nkv, hd, _ = _attn_dims(cfg)
    b, t, _ = qkv.shape
    q = qkv[..., : nh * hd].reshape(b, t, nh, hd)
    k = qkv[..., nh * hd : (nh + nkv) * hd].reshape(b, t, nkv, hd)
    v = qkv[..., (nh + nkv) * hd :].reshape(b, t, nkv, hd)
    return q, k, v


def _sdpa_causal(q, k, v, offset: int = 0):
    """Causal softmax(QK^T/sqrt(d))V with GQA broadcast, fp32 softmax.

    q (b, tq, nh, hd); k/v (b, tk, nkv, hd); ``offset`` = absolute position
    of q[0] minus that of k[0] (for decode with cache).
    """
    b, tq, nh, hd = q.shape
    nkv = k.shape[2]
    rep = nh // nkv
    qh = q.reshape(b, tq, nkv, rep, hd)
    scores = jnp.einsum(
        "bqgrh,bkgh->bgrqk", qh, k, preferred_element_type=jnp.float32
    ) / math.sqrt(hd)
    qpos = jnp.arange(tq)[:, None] + offset
    kpos = jnp.arange(k.shape[1])[None, :]
    scores = jnp.where(qpos >= kpos, scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bgrqk,bkgh->bqgrh", w, v, preferred_element_type=jnp.float32)
    return out.reshape(b, tq, nh, hd).astype(q.dtype)


def attention_mixer(
    params: dict,
    cfg: ModelConfig,
    u: jax.Array,
    initial_state=None,
    return_final_state: bool = False,
    seq_ctx=None,
):
    """Full-sequence causal attention.  u (b, t, d) -> (b, t, d).

    The decode "state" is the (k_cache, v_cache, length) triple; for the
    full-sequence path with ``return_final_state`` the caches hold the whole
    sequence (used by prefill).
    """
    nh, nkv, hd, rot = _attn_dims(cfg)
    b, t, _ = u.shape
    compute_dtype = jnp.dtype(cfg.compute_dtype)

    qkv = linear(params["wqkv"], u, compute_dtype)
    q, k, v = _split_qkv(qkv, cfg)
    if rot > 0:
        angles = rope_angles(jnp.arange(t), rot, cfg.rope_theta)
        q = apply_rope(q, angles)
        k = apply_rope(k, angles)

    from mamba_distributed_tpu.ops.pallas.common import resolve_attn_impl

    attn_impl = resolve_attn_impl(cfg.attn_impl)
    if seq_ctx is not None:
        if cfg.attn_sp_impl == "ulysses":
            from mamba_distributed_tpu.parallel.ulysses import (
                ulysses_attention,
            )

            out = ulysses_attention(seq_ctx, q, k, v, impl=attn_impl)
        else:
            from mamba_distributed_tpu.parallel.ring_attention import (
                ring_attention,
            )

            out = ring_attention(seq_ctx, q, k, v, impl=attn_impl)
    elif attn_impl == "pallas":
        from mamba_distributed_tpu.ops.pallas.attention_kernels import (
            flash_sdpa_causal,
        )

        # flash kernel: online softmax in VMEM, fully-future blocks skipped
        out = flash_sdpa_causal(q, k, v)
    else:
        from mamba_distributed_tpu.ops.blockwise_attention import (
            blockwise_sdpa_causal,
        )

        # O(t*block) memory — never materializes the (t, t) score tensor
        # (config 5 at T=8192); the tiny-t decode path keeps _sdpa_causal
        out = blockwise_sdpa_causal(q, k, v)
    # remat_policy="mixer" save point (models/lm.py:_remat)
    out = checkpoint_name(out, "mixer_out")
    y = linear(params["out_proj"], out.reshape(b, t, nh * hd), compute_dtype)
    if return_final_state:
        return y, (k, v, jnp.array(t, jnp.int32))
    return y


def init_attention_state(cfg: ModelConfig, batch: int, max_len: int,
                         dtype=None):
    """KV caches in the compute dtype — matching what attention_mixer's
    prefill path produces, so init- and prefill-built states share avals."""
    nh, nkv, hd, _ = _attn_dims(cfg)
    if dtype is None:
        dtype = jnp.dtype(cfg.compute_dtype)
    k = jnp.zeros((batch, max_len, nkv, hd), dtype)
    v = jnp.zeros((batch, max_len, nkv, hd), dtype)
    return k, v, jnp.array(0, jnp.int32)


def attention_mixer_step(params: dict, cfg: ModelConfig, u_t: jax.Array, state):
    """Single-token decode with a fixed-capacity KV cache.

    u_t (b, d); state = (k_cache (b, L, nkv, hd), v_cache, length).
    """
    nh, nkv, hd, rot = _attn_dims(cfg)
    b, _ = u_t.shape
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    k_cache, v_cache, length = state

    qkv = linear(params["wqkv"], u_t[:, None, :], compute_dtype)
    q, k, v = _split_qkv(qkv, cfg)
    if rot > 0:
        angles = rope_angles(length[None], rot, cfg.rope_theta)
        q = apply_rope(q, angles)
        k = apply_rope(k, angles)

    k_cache = jax.lax.dynamic_update_slice_in_dim(k_cache, k.astype(k_cache.dtype), length, axis=1)
    v_cache = jax.lax.dynamic_update_slice_in_dim(v_cache, v.astype(v_cache.dtype), length, axis=1)
    # mask out cache slots beyond the current length via the causal offset
    out = _sdpa_causal(q, k_cache, v_cache, offset=length)
    y = linear(params["out_proj"], out.reshape(b, nh * hd), compute_dtype)
    return y, (k_cache, v_cache, length + 1)
