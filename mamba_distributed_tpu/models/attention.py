"""Causal self-attention with RoPE (hybrid Jamba-style layers).

Functional equivalent of ``mamba_ssm.modules.mha.MHA`` as used by hybrid
configs via ``attn_layer_idx``/``attn_cfg`` (mamba-ssm 2.2.2; the reference
never enables it — SURVEY.md §2.3 — but BASELINE.json config 5 requires it).

GQA layout: packed qkv projection, ``num_heads`` query heads sharing
``num_kv_heads`` KV heads; rotary embedding on the leading ``rotary_dim``
of each head.  Under sequence parallelism the score/value contraction runs
as ring attention over the mesh's ``seq`` axis (parallel/ring_attention.py).

Decode state is a PAGED KV cache with per-row lengths (the ragged/paged
attention pattern — see the section marker below): rows of one decode
batch may sit at different sequence positions, which is what admits
hybrid models into the serving slot pool (serving/state_cache.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from mamba_distributed_tpu.config import ModelConfig
from mamba_distributed_tpu.models.common import init_linear, linear


def _attn_dims(cfg: ModelConfig):
    nh = cfg.effective_attn_num_heads
    nkv = cfg.effective_attn_num_kv_heads
    hd = cfg.effective_attn_head_dim
    # -1 => full head dim; 0 => no rotary (mamba_ssm's rotary_emb_dim)
    rot = hd if cfg.attn_rotary_dim < 0 else cfg.attn_rotary_dim
    return nh, nkv, hd, rot


def init_attention_params(key: jax.Array, cfg: ModelConfig) -> dict:
    nh, nkv, hd, _ = _attn_dims(cfg)
    k_qkv, k_out = jax.random.split(key)
    params = {
        "wqkv": init_linear(k_qkv, cfg.d_model, (nh + 2 * nkv) * hd, cfg.proj_bias),
        "out_proj": init_linear(k_out, nh * hd, cfg.d_model, cfg.proj_bias),
    }
    if cfg.rescale_prenorm_residual:
        n_residuals = 2 if cfg.d_intermediate > 0 else 1
        params["out_proj"]["kernel"] = params["out_proj"]["kernel"] / math.sqrt(
            n_residuals * cfg.n_layer
        )
    return params


def rope_angles(positions: jax.Array, rotary_dim: int, theta: float) -> jax.Array:
    """(t,) or (b, t) int positions -> positions.shape + (rotary_dim/2,)
    angles.  Per-ROW positions are what lets slots at different sequence
    positions share one decode batch (the paged-KV serving pool)."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, rotary_dim, 2, dtype=jnp.float32) / rotary_dim)
    )
    return positions.astype(jnp.float32)[..., None] * inv_freq


def apply_rope(x: jax.Array, angles: jax.Array) -> jax.Array:
    """Rotate the leading ``2*angles.shape[-1]`` channels of each head.

    x (b, t, h, hd); angles (t, rot/2) shared across the batch, or
    (b, t, rot/2) per-row (paged decode: every row sits at its own
    position).  Rotate-half (GPT-NeoX, non-interleaved) convention on
    the rotary slice — pairs are (x[i], x[i + rot/2]) — matching the
    flash-attn RotaryEmbedding default (``interleaved=False``) that
    mamba_ssm's MHA layers use, so hybrid checkpoints import with
    bit-compatible attention semantics.  The tail past the rotary slice
    passes through.
    """
    rot = 2 * angles.shape[-1]
    xr, x_pass = x[..., :rot], x[..., rot:]
    xf = xr.astype(jnp.float32)
    x1, x2 = xf[..., : rot // 2], xf[..., rot // 2 :]
    if angles.ndim == 2:
        angles = angles[None]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    o1 = x1 * cos - x2 * sin
    o2 = x1 * sin + x2 * cos
    out = jnp.concatenate([o1, o2], axis=-1).astype(x.dtype)
    return jnp.concatenate([out, x_pass], axis=-1) if x_pass.size else out


def _split_qkv(qkv: jax.Array, cfg: ModelConfig):
    nh, nkv, hd, _ = _attn_dims(cfg)
    b, t, _ = qkv.shape
    q = qkv[..., : nh * hd].reshape(b, t, nh, hd)
    k = qkv[..., nh * hd : (nh + nkv) * hd].reshape(b, t, nkv, hd)
    v = qkv[..., (nh + nkv) * hd :].reshape(b, t, nkv, hd)
    return q, k, v


def _sdpa_causal(q, k, v, offset: int = 0):
    """Causal softmax(QK^T/sqrt(d))V with GQA broadcast, fp32 softmax.

    q (b, tq, nh, hd); k/v (b, tk, nkv, hd); ``offset`` = absolute position
    of q[0] minus that of k[0] (for decode with cache).
    """
    b, tq, nh, hd = q.shape
    nkv = k.shape[2]
    rep = nh // nkv
    qh = q.reshape(b, tq, nkv, rep, hd)
    scores = jnp.einsum(
        "bqgrh,bkgh->bgrqk", qh, k, preferred_element_type=jnp.float32
    ) / math.sqrt(hd)
    qpos = jnp.arange(tq)[:, None] + offset
    kpos = jnp.arange(k.shape[1])[None, :]
    scores = jnp.where(qpos >= kpos, scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bgrqk,bkgh->bqgrh", w, v, preferred_element_type=jnp.float32)
    return out.reshape(b, tq, nh, hd).astype(q.dtype)


def attention_mixer(
    params: dict,
    cfg: ModelConfig,
    u: jax.Array,
    initial_state=None,
    return_final_state: bool = False,
    seq_ctx=None,
):
    """Full-sequence causal attention.  u (b, t, d) -> (b, t, d).

    With ``return_final_state`` the raw (k, v) of the whole sequence are
    returned alongside; the caller (models/lm.lm_prefill) packs them into
    the paged decode cache (``pack_attention_pages``).
    """
    nh, nkv, hd, rot = _attn_dims(cfg)
    b, t, _ = u.shape
    compute_dtype = jnp.dtype(cfg.compute_dtype)

    qkv = linear(params["wqkv"], u, compute_dtype)
    q, k, v = _split_qkv(qkv, cfg)
    if rot > 0:
        angles = rope_angles(jnp.arange(t), rot, cfg.rope_theta)
        q = apply_rope(q, angles)
        k = apply_rope(k, angles)

    from mamba_distributed_tpu.ops.pallas.common import resolve_attn_impl

    attn_impl = resolve_attn_impl(cfg.attn_impl)
    if seq_ctx is not None:
        if cfg.attn_sp_impl == "ulysses":
            from mamba_distributed_tpu.parallel.ulysses import (
                ulysses_attention,
            )

            out = ulysses_attention(seq_ctx, q, k, v, impl=attn_impl)
        else:
            from mamba_distributed_tpu.parallel.ring_attention import (
                ring_attention,
            )

            out = ring_attention(seq_ctx, q, k, v, impl=attn_impl)
    elif attn_impl == "pallas":
        from mamba_distributed_tpu.ops.pallas.attention_kernels import (
            flash_sdpa_causal,
        )

        # flash kernel: online softmax in VMEM, fully-future blocks skipped
        out = flash_sdpa_causal(q, k, v)
    else:
        from mamba_distributed_tpu.ops.blockwise_attention import (
            blockwise_sdpa_causal,
        )

        # O(t*block) memory — never materializes the (t, t) score tensor
        # (config 5 at T=8192); the tiny-t paged decode path keeps the
        # explicit-mask _sdpa_positions
        out = blockwise_sdpa_causal(q, k, v)
    # remat_policy="mixer" save point (models/lm.py:_remat)
    out = checkpoint_name(out, "mixer_out")
    y = linear(params["out_proj"], out.reshape(b, t, nh * hd), compute_dtype)
    if return_final_state:
        return y, (k, v)
    return y


# ---------------------------------------------------------------------------
# Paged decode-time KV cache ("Ragged Paged Attention", PAPERS.md)
#
# The decode cache is a pool of fixed-size pages plus per-ROW metadata:
#
#   k_pages / v_pages  (P, nkv, page, hd)   physical pages, HEAD-MAJOR;
#                                           page 0 is a reserved trash
#                                           page that masked-out rows
#                                           write into
#
# Head-major storage is the kernel-native layout: the Pallas ragged
# kernels (ops/pallas/attention_kernels.py) block pages as (page, hd)
# tiles per (page, kv-head) cell, so storing (nkv, page, hd) lets the
# BlockSpec index map address a page's head slice directly — no per-call
# transpose of the whole pool on the decode/prefill hot path.  The lax
# fallback pays one extra axis move inside its (already materializing)
# gather instead.
#   page_table         (b, W) int32         row r's logical page j lives
#                                           in physical page table[r, j]
#   lengths            (b,) int32           tokens cached per row
#
# Rows at DIFFERENT sequence positions share one batch (per-row RoPE
# angles, per-row causal masks, per-row scatter writes), which is what
# lets hybrid models into the serving slot pool (serving/state_cache.py);
# KV HBM is O(pages in use) because pages are handed out by a host-side
# allocator on admission and recycled on evict.  ``generate()`` uses the
# same structure with an identity table — the SAME decode step serves
# both, which is what keeps engine<->generate() token parity exact.
#
# Bit-stability note: masked attention over a zero-padded key axis is
# bit-identical across padded widths at 8-lane granularity (verified on
# CPU XLA; cfg enforces kv_page_tokens % 8 == 0), so the engine's
# page-count bucket may differ from generate()'s without perturbing
# token streams.
# ---------------------------------------------------------------------------


def attention_page_count(cfg: ModelConfig, max_len: int) -> int:
    """Pages needed per row for ``max_len`` tokens (at least one)."""
    return max(1, -(-max_len // cfg.kv_page_tokens))


# ---------------------------------------------------------------------------
# Int8 KV page quantization (cfg.kv_page_dtype == "int8"; ops/quant.py
# holds the shared round/clip math and docs/SERVING.md "Quantized
# serving" the layout).  An int8 layer cache is a 4-tuple
# ``(k_pages int8, v_pages int8, k_scale f32 (P, nkv), v_scale f32
# (P, nkv))`` — one symmetric scale per (physical page, kv head), so a
# page's whole (page, hd) tile dequantizes with ONE scalar multiply
# (what the Pallas page walk fuses in-register).  The scale-update rule
# needs NO read of old page content:
#
#   new_scale = max(old_scale if the page holds PRIOR tokens of this
#                   sequence (write offset > 0 within the page),
#                   absmax(fresh rows) / 127)
#
# because old_scale already bounds the page's stored values.  Old rows
# re-express under the new scale (``round(q_old * old/new)`` — the
# ratio is <= 1 whenever prior content exists, so requantization only
# ever rounds, never clips real data), and a RECYCLED page's stale
# scale is ignored outright (no prior content => fresh scale), so
# garbage from an evicted tenant can never inflate a live page's step
# size.  The lax fallback and both ragged kernels implement the same
# rule, so kernel-vs-lax stays within fp tolerance at every ragged mix.
# ---------------------------------------------------------------------------


def _kv_page_scale_init(n_pages: int, nkv: int) -> jax.Array:
    """Fresh scale array: ones — never read before the first write to a
    page sets it (the no-prior-content branch ignores old scales), and
    finite so trash-page dequantization can never produce NaN/inf."""
    return jnp.ones((n_pages, nkv), jnp.float32)


def init_attention_state(cfg: ModelConfig, batch: int, max_len: int,
                         dtype=None):
    """Empty paged KV cache for one attention layer: (k_pages, v_pages)
    of shape (1 + batch*W, nkv, page, hd) — HEAD-MAJOR, page 0 is the
    trash page — in the compute dtype, matching what the prefill path
    produces.  The shared (page_table, lengths) metadata is built once
    per model by ``attention_page_meta`` (models/lm.init_lm_state).

    ``cfg.kv_page_dtype="int8"`` returns the quantized 4-tuple instead:
    int8 pages plus the per-(page, kv-head) f32 scale arrays (see the
    section comment above) — page bytes halve, which is the serving
    pool's capacity doubling (``quant_kv_capacity``)."""
    nh, nkv, hd, _ = _attn_dims(cfg)
    quant = cfg.kv_quantized and dtype is None
    if dtype is None:
        dtype = jnp.int8 if quant else jnp.dtype(cfg.compute_dtype)
    W = attention_page_count(cfg, max_len)
    P = 1 + batch * W
    shape = (P, nkv, cfg.kv_page_tokens, hd)
    # two INDEPENDENT allocations: returning one aliased array twice
    # would blow up any donating jit downstream ("donate the same
    # buffer twice") if a caller ever skips the re-stacking copy
    if quant:
        return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                _kv_page_scale_init(P, nkv), _kv_page_scale_init(P, nkv))
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def attention_page_meta(cfg: ModelConfig, batch: int, max_len: int):
    """Identity page table + zero lengths for a private (non-pooled)
    paged cache: row r owns physical pages [1 + r*W, 1 + (r+1)*W)."""
    W = attention_page_count(cfg, max_len)
    tbl = 1 + jnp.arange(batch * W, dtype=jnp.int32).reshape(batch, W)
    return tbl, jnp.zeros((batch,), jnp.int32)


def pack_attention_pages(cfg: ModelConfig, k: jax.Array, v: jax.Array,
                         max_len: int):
    """(b, t, nkv, hd) full-sequence K/V -> identity-paged head-major
    (k_pages, v_pages) with capacity ``max_len`` (lm_prefill's state
    packing).  Int8 pools additionally quantize each (page, kv-head)
    tile under its own absmax scale and return the 4-tuple."""
    b, t, nkv, hd = k.shape
    pg = cfg.kv_page_tokens
    W = attention_page_count(cfg, max_len)

    def pack(x):
        x = jnp.pad(x, ((0, 0), (0, W * pg - t), (0, 0), (0, 0)))
        x = x.reshape(b, W, pg, nkv, hd)
        x = jnp.moveaxis(x, 3, 2).reshape(b * W, nkv, pg, hd)
        return jnp.concatenate([jnp.zeros_like(x[:1]), x], axis=0)

    if cfg.kv_quantized:
        from mamba_distributed_tpu.ops.quant import (
            Q_MAX,
            SCALE_EPS,
            kv_quantize,
        )

        def pack_q(x):
            pages = pack(x.astype(jnp.float32))           # (P, nkv, pg, hd)
            absmax = jnp.max(jnp.abs(pages), axis=(2, 3))  # (P, nkv)
            scale = jnp.maximum(absmax / Q_MAX, SCALE_EPS)
            q = kv_quantize(pages, scale[:, :, None, None])
            return q.astype(jnp.int8), scale

        kq, ks = pack_q(k)
        vq, vs = pack_q(v)
        return kq, vq, ks, vs
    return pack(k), pack(v)


def gather_kv_pages(k_pages: jax.Array, v_pages: jax.Array,
                    page_table: jax.Array,
                    live_pages: jax.Array | None = None,
                    k_scale: jax.Array | None = None,
                    v_scale: jax.Array | None = None,
                    dtype=None):
    """Reassemble each row's logical KV view: (P, nkv, pg, hd) head-major
    pages + (b, W) table -> (b, W*pg, nkv, hd).  The lax fallback path —
    the Pallas ragged kernels (ops/pallas/attention_kernels.py) walk the
    table in-kernel instead of materializing this (and read the
    head-major pages without the axis move this gather folds in).

    ``live_pages`` (b,) int32 — logical pages actually LIVE per row —
    redirects table entries at or past each row's live extent to the
    trash page, so the gather's read traffic touches only live pages
    (plus the one trash page, hot in cache) instead of every reserved
    page up to the table width: O(live tokens), not O(pool), per call —
    what makes the fallback viable for CPU-serving deployments.  Safe
    bit-exactly: every position in a dead page is already hard-masked
    to -inf by the callers' causal/position bounds (``_sdpa_positions``
    ``jnp.where``s masked scores regardless of the gathered values), so
    the substitution can never change a live lane.

    ``k_scale``/``v_scale`` (int8 pools: (P, nkv) per-page-per-head
    scales) dequantize the gathered pages into ``dtype`` — the lax
    mirror of the kernels' in-register scale multiply.  Trash-page
    rows dequantize with the trash scale (finite garbage, masked as
    above)."""
    b, W = page_table.shape
    _, nkv, pg, hd = k_pages.shape
    if live_pages is not None:
        page_table = jnp.where(
            jnp.arange(W)[None, :] < live_pages[:, None], page_table, 0
        )
    if dtype is None:
        dtype = jnp.float32

    def gather(pages, scales):
        x = pages[page_table]                            # (b, W, nkv, pg, hd)
        if scales is not None:
            x = x.astype(dtype) * scales[page_table][
                ..., None, None].astype(dtype)
        x = jnp.moveaxis(x, 2, 3)                        # (b, W, pg, nkv, hd)
        return x.reshape(b, W * pg, nkv, hd)

    return gather(k_pages, k_scale), gather(v_pages, v_scale)


def _sdpa_positions(q, k, v, qpos):
    """Masked SDPA with per-row absolute query positions.

    q (b, tq, nh, hd); k/v (b, L, nkv, hd) — the gathered logical cache
    view; qpos (b, tq) int32 — query i of row r may attend cache
    position j iff ``j <= qpos[r, i]`` (the cache holds positions
    [0, lengths) plus this call's freshly written tokens, so the bound
    is exactly the causal rule).
    """
    b, tq, nh, hd = q.shape
    nkv = k.shape[2]
    rep = nh // nkv
    qh = q.reshape(b, tq, nkv, rep, hd)
    scores = jnp.einsum(
        "bqgrh,bkgh->bgrqk", qh, k, preferred_element_type=jnp.float32
    ) / math.sqrt(hd)
    kpos = jnp.arange(k.shape[1])
    mask = qpos[:, None, None, :, None] >= kpos[None, None, None, None, :]
    scores = jnp.where(mask, scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bgrqk,bkgh->bqgrh", w, v, preferred_element_type=jnp.float32)
    return out.reshape(b, tq, nh, hd).astype(q.dtype)


def attention_mixer_step(params: dict, cfg: ModelConfig, u_t: jax.Array,
                         kv, page_table: jax.Array, lengths: jax.Array,
                         write_mask: jax.Array | None = None):
    """Single-token decode against the paged KV cache.

    u_t (b, d); kv = (k_pages, v_pages) — or the int8 4-tuple with the
    per-(page, kv-head) scales; page_table (b, W); lengths (b,)
    — the row's token count BEFORE this step (the new token lands at
    cache position ``lengths[r]``).  ``write_mask`` (b,) bool routes
    masked rows' KV writes to the trash page and is how the serving tick
    protects recycled pages from dead slots; the shared ``lengths``
    update happens once per model step in models/lm.py.

    Int8 pools make the write page-granular: the target page is read,
    old rows re-expressed under the (possibly grown) scale, the fresh
    row quantized in, and the (page, scale) pair scattered back — the
    scale-update rule in the section comment above, shared bit-for-bit
    with the chunk path and mirrored by the kernels.  Masked rows'
    page AND scale writes land on the trash page as before.

    Returns (y (b, d), kv') with kv' the same arity as ``kv``.
    """
    nh, nkv, hd, rot = _attn_dims(cfg)
    b, _ = u_t.shape
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    quant = len(kv) == 4
    if quant:
        k_pages, v_pages, k_scale, v_scale = kv
    else:
        k_pages, v_pages = kv
        k_scale = v_scale = None
    pg = cfg.kv_page_tokens
    W = page_table.shape[1]

    qkv = linear(params["wqkv"], u_t[:, None, :], compute_dtype)
    q, k, v = _split_qkv(qkv, cfg)
    if rot > 0:
        angles = rope_angles(lengths[:, None], rot, cfg.rope_theta)
        q = apply_rope(q, angles)
        k = apply_rope(k, angles)

    mask = (
        jnp.ones((b,), bool) if write_mask is None else write_mask
    )
    pidx = jnp.clip(lengths // pg, 0, W - 1)
    phys = jnp.where(
        mask, jnp.take_along_axis(page_table, pidx[:, None], axis=1)[:, 0], 0
    )
    off = jnp.where(mask, lengths % pg, 0)
    if quant:
        from mamba_distributed_tpu.ops.quant import (
            Q_MAX,
            SCALE_EPS,
            kv_quantize,
            kv_requant,
        )

        def qwrite(pages, scales, row):
            # row (b, nkv, hd): requantize the whole target page under
            # the updated scale, insert the fresh row at ``off``
            old_q = pages[phys]                       # (b, nkv, pg, hd)
            old_s = scales[phys]                      # (b, nkv)
            has_prior = (off > 0)[:, None]            # page holds this
            # sequence's earlier tokens iff the write offset is interior
            amax = jnp.max(jnp.abs(row.astype(jnp.float32)), axis=-1)
            new_s = jnp.maximum(jnp.maximum(
                jnp.where(has_prior, old_s, 0.0), amax / Q_MAX), SCALE_EPS)
            ratio = jnp.where(has_prior, old_s / new_s, 0.0)
            req = kv_requant(old_q, ratio[..., None, None])
            q_row = kv_quantize(row, new_s[..., None])
            onehot = jnp.arange(pg)[None, :] == off[:, None]   # (b, pg)
            page = jnp.where(onehot[:, None, :, None],
                             q_row[:, :, None, :], req)
            return (pages.at[phys].set(page.astype(pages.dtype)),
                    scales.at[phys].set(new_s))

        k_pages, k_scale = qwrite(k_pages, k_scale, k[:, 0])
        v_pages, v_scale = qwrite(v_pages, v_scale, v[:, 0])
    else:
        # head-major pages: the token offset sits one axis past the
        # heads, so the (b,) phys/off pair scatters a (b, nkv, hd) row
        # block per write
        k_pages = k_pages.at[phys, :, off].set(k[:, 0].astype(k_pages.dtype))
        v_pages = v_pages.at[phys, :, off].set(v[:, 0].astype(v_pages.dtype))

    from mamba_distributed_tpu.ops.pallas.common import resolve_attn_impl

    qpos = jnp.minimum(lengths, W * pg - 1)
    if resolve_attn_impl(cfg.attn_impl) == "pallas":
        from mamba_distributed_tpu.ops.pallas.attention_kernels import (
            ragged_paged_decode_attention,
        )

        # kv_len = tokens readable AFTER the write; the kernel skips
        # whole pages past it, so decode cost tracks live tokens (int8
        # pools: dequant fused into the page walk via the prefetched
        # scales)
        out = ragged_paged_decode_attention(
            q[:, 0], k_pages, v_pages, page_table,
            jnp.minimum(qpos + 1, W * pg),
            k_scale=k_scale, v_scale=v_scale,
        )[:, None]
    else:
        # tokens readable after the write = qpos + 1 per row: gather
        # only the pages that hold them (the rest go to trash — masked
        # anyway), so decode cost tracks live tokens off-TPU too
        kk, vv = gather_kv_pages(
            k_pages, v_pages, page_table, (qpos + pg) // pg,
            k_scale=k_scale, v_scale=v_scale, dtype=compute_dtype,
        )
        out = _sdpa_positions(q, kk, vv, qpos[:, None])
    y = linear(params["out_proj"], out.reshape(b, nh * hd), compute_dtype)
    if quant:
        return y, (k_pages, v_pages, k_scale, v_scale)
    return y, (k_pages, v_pages)


def _chunk_page_scales(k, v, real, page_table, lengths, n_real,
                       k_scale, v_scale, pg: int):
    """Post-chunk-write per-(page, kv-head) scales (int8 pools).

    Applies the scale-update rule (section comment above) to every page
    in the chunk's write window — ``[lengths, lengths + n_real)`` per
    row — WITHOUT reading page content: old scales bound old values, so
    ``new = max(old if prior content else 0, chunk absmax / 127)``.
    Returns ``(k_scale', v_scale', takes)`` with the updated (P, nkv)
    arrays (non-window pages untouched; trash-page entries are garbage
    by the usual contract) and the (b, W) write-window mask.  Shared by
    the lax fallback and the Pallas path (the kernel takes the OLD and
    NEW arrays scalar-prefetched and re-derives the requant ratio per
    visited page), so the two paths can never disagree on a scale.
    """
    from mamba_distributed_tpu.ops.quant import Q_MAX, SCALE_EPS

    b, c = real.shape
    W = page_table.shape[1]
    total = lengths + n_real
    pad = c - n_real
    pos = lengths[:, None] + jnp.arange(c)[None, :] - pad[:, None]
    pageidx = jnp.clip(jnp.maximum(pos, 0) // pg, 0, W - 1)
    wcol = jnp.arange(W)[None, :]
    takes = ((wcol * pg < total[:, None])
             & ((wcol + 1) * pg > lengths[:, None])
             & (n_real > 0)[:, None])                      # (b, W)
    has_prior = lengths[:, None] > wcol * pg               # (b, W)
    # which chunk rows land in which window page (pads excluded)
    oh = (pageidx[:, :, None] == wcol[:, None, :]) & real[:, :, None]

    def update(x, scales):
        absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)  # (b,c,nkv)
        amax = jnp.max(
            jnp.where(oh[..., None], absmax[:, :, None, :], 0.0), axis=1
        )                                                  # (b, W, nkv)
        old = scales[page_table]                           # (b, W, nkv)
        new = jnp.maximum(jnp.maximum(
            jnp.where(has_prior[..., None], old, 0.0), amax / Q_MAX),
            SCALE_EPS)
        new = jnp.where(takes[..., None], new, old)
        dst = jnp.where(takes, page_table, 0)              # no-writes -> trash
        return scales.at[dst].set(new)

    return update(k, k_scale), update(v, v_scale), takes


def attention_mixer_chunk(params: dict, cfg: ModelConfig, u: jax.Array,
                          kv, page_table: jax.Array, lengths: jax.Array,
                          token_mask: jax.Array | None = None):
    """One prefill CHUNK against the paged cache: write the chunk's real
    tokens' K/V into this row's pages at positions [lengths, lengths +
    n_real), then attend every chunk query over the page view (prefix +
    the freshly written chunk — intra-chunk causality falls out of the
    per-position bound).

    u (b, c, d); token_mask (b, c) {0,1} marks real tokens — the pad is
    a LEFT prefix (serving/prefill.chunk_inputs), so real token j of the
    chunk sits at absolute position ``lengths[r] + j`` regardless of the
    pad, and pad queries (clamped to position 0) produce garbage that
    dies with their discarded stream positions.  The shared ``lengths``
    advance (+ n_real) happens once per model chunk in models/lm.py.

    When ``cfg.attn_impl`` resolves to "pallas" the write + attend run as
    ONE Pallas kernel over the head-major page pool
    (``ragged_paged_prefill_attention``): the chunk's real K/V are fused
    into the page walk and pages past ``lengths + n_real`` are skipped,
    so chunk cost tracks live tokens instead of pool width.  The lax
    fallback (explicit ``attn_impl="xla"``, or auto off-TPU) keeps the
    scatter + full-view gather + dense SDPA.

    Int8 pools (``kv`` the 4-tuple): the post-write scales are planned
    host-of-kernel in ``_chunk_page_scales`` (no page reads needed),
    then the write-window pages requantize-and-merge — in-kernel for
    the Pallas path (old/new scale arrays scalar-prefetched, fresh
    rows quantized before the one-hot merge, attend on the dequantized
    merged tile), in XLA for the fallback — and the attend runs over
    the dequantized view.  Same math both paths.

    Returns (y (b, c, d), kv') with kv' the same arity as ``kv``.
    """
    nh, nkv, hd, rot = _attn_dims(cfg)
    b, c, _ = u.shape
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    quant = len(kv) == 4
    if quant:
        k_pages, v_pages, k_scale, v_scale = kv
    else:
        k_pages, v_pages = kv
        k_scale = v_scale = None
    pg = cfg.kv_page_tokens
    W = page_table.shape[1]

    qkv = linear(params["wqkv"], u, compute_dtype)
    q, k, v = _split_qkv(qkv, cfg)
    if token_mask is None:
        real = jnp.ones((b, c), bool)
    else:
        real = token_mask > 0.5
    pad = c - jnp.sum(real.astype(jnp.int32), axis=1)          # (b,)
    pos = lengths[:, None] + jnp.arange(c)[None, :] - pad[:, None]
    posc = jnp.maximum(pos, 0)                                  # (b, c)
    if rot > 0:
        angles = rope_angles(posc, rot, cfg.rope_theta)
        q = apply_rope(q, angles)
        k = apply_rope(k, angles)

    if quant:
        ks_new, vs_new, takes = _chunk_page_scales(
            k, v, real, page_table, lengths, c - pad, k_scale, v_scale, pg
        )

    from mamba_distributed_tpu.ops.pallas.common import resolve_attn_impl

    if resolve_attn_impl(cfg.attn_impl) == "pallas":
        from mamba_distributed_tpu.ops.pallas.attention_kernels import (
            ragged_paged_prefill_attention,
        )

        out, k_pages, v_pages = ragged_paged_prefill_attention(
            q, k, v, k_pages, v_pages, page_table, lengths, c - pad,
            **({} if not quant else dict(
                k_scale_old=k_scale, v_scale_old=v_scale,
                k_scale_new=ks_new, v_scale_new=vs_new,
            )),
        )
        if quant:
            k_scale, v_scale = ks_new, vs_new
    elif quant:
        from mamba_distributed_tpu.ops.quant import kv_quantize, kv_requant

        # the chunk's write WINDOW — the only pages that requantize or
        # write back — spans at most ceil(c/pg)+1 logical pages starting
        # at lengths//pg, so the merge gathers/scatters O(chunk) pages
        # per row, never O(table width) (the same live-traffic rule the
        # bf16 fallback keeps via gather_kv_pages(live_pages=))
        Wc = min(W, -(-c // pg) + 1)
        j0 = lengths // pg                              # (b,)
        wj = j0[:, None] + jnp.arange(Wc)[None, :]      # (b, Wc) logical
        in_range = wj < W
        wjc = jnp.where(in_range, wj, W - 1)
        wtbl = jnp.take_along_axis(page_table, wjc, axis=1)
        takes_w = jnp.take_along_axis(takes, wjc, axis=1) & in_range
        has_prior = (lengths[:, None] > wj * pg) & in_range
        # window-local chunk-token coordinates (real tokens only: posc
        # >= lengths >= j0*pg and posc < lengths + c <= (j0+Wc)*pg)
        lpos = jnp.clip(posc - (j0 * pg)[:, None], 0, Wc * pg - 1)
        lpidx = lpos // pg                              # (b, c)
        dst = jnp.where(takes_w, wtbl, 0)

        def merge(pages, old_scales, new_scales, x):
            # requantize window pages under their new scales, then
            # scatter the chunk's quantized rows into the flat view
            old_q = pages[wtbl]                       # (b, Wc, nkv, pg, hd)
            old_s = old_scales[wtbl]                  # (b, Wc, nkv)
            new_s = new_scales[wtbl]
            ratio = jnp.where(has_prior[..., None], old_s / new_s, 0.0)
            req = kv_requant(old_q, ratio[..., None, None])
            row_s = jnp.take_along_axis(new_s, lpidx[:, :, None], axis=1)
            q_rows = kv_quantize(x, row_s[..., None])  # (b, c, nkv, hd)
            view = jnp.moveaxis(req, 3, 2).reshape(b, Wc * pg, nkv, hd)
            view = jnp.concatenate(                    # pad slot for pads
                [view, jnp.zeros((b, 1, nkv, hd), view.dtype)], axis=1)
            idx = jnp.where(real, lpos, Wc * pg)
            view = view.at[jnp.arange(b)[:, None], idx].set(q_rows)
            merged = jnp.moveaxis(
                view[:, :-1].reshape(b, Wc, pg, nkv, hd), 2, 3
            )
            return pages.at[dst].set(merged.astype(pages.dtype))

        k_pages = merge(k_pages, k_scale, ks_new, k)
        v_pages = merge(v_pages, v_scale, vs_new, v)
        k_scale, v_scale = ks_new, vs_new
        tokens = jnp.minimum(lengths + (c - pad), W * pg)
        kk, vv = gather_kv_pages(
            k_pages, v_pages, page_table,
            jnp.maximum((tokens + pg - 1) // pg, 1),
            k_scale=k_scale, v_scale=v_scale, dtype=compute_dtype,
        )
        out = _sdpa_positions(q, kk, vv, jnp.minimum(posc, W * pg - 1))
    else:
        pidx = jnp.clip(posc // pg, 0, W - 1)
        phys = jnp.where(
            real, jnp.take_along_axis(page_table, pidx, axis=1), 0
        )
        off = jnp.where(real, posc % pg, 0)
        # head-major pages: the (b, c) phys/off pair scatters
        # (b, c, nkv, hd) blocks one axis past the heads
        k_pages = k_pages.at[phys, :, off].set(k.astype(k_pages.dtype))
        v_pages = v_pages.at[phys, :, off].set(v.astype(v_pages.dtype))
        # live extent after this chunk's write = prefix + its real
        # tokens; pages past it gather as trash (fully masked), so the
        # chunk's fallback cost tracks live tokens, not table width
        # (at least one page: a degenerate all-pad row clamps its
        # queries to position 0, which must stay a real gather)
        tokens = jnp.minimum(lengths + (c - pad), W * pg)
        kk, vv = gather_kv_pages(
            k_pages, v_pages, page_table,
            jnp.maximum((tokens + pg - 1) // pg, 1),
        )
        out = _sdpa_positions(q, kk, vv, jnp.minimum(posc, W * pg - 1))
    y = linear(params["out_proj"], out.reshape(b, c, nh * hd), compute_dtype)
    if quant:
        return y, (k_pages, v_pages, k_scale, v_scale)
    return y, (k_pages, v_pages)
