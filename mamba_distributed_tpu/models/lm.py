"""Full language model: embedding -> N blocks -> final norm -> tied head.

Semantics match the reference wrapper + its dep
(``/root/reference/model.py:25-47`` — loss is plain cross-entropy against
the loader's pre-shifted targets — and ``mamba_ssm.models.mixer_seq_simple.
MixerModel``/``create_block``: prenorm blocks, fp32 residual stream, tied
embeddings, fused add+RMSNorm between blocks, optional gated MLP when
``d_intermediate > 0``, optional attention layers at ``attn_layer_idx``).

TPU-native structure: homogeneous stacks run as ``lax.scan`` over
layer-stacked parameters (one compiled block body regardless of depth,
which is also the FSDP-friendly layout — shard the non-layer axes and the
scan slices locally).  Hybrid stacks with a *periodic* attention pattern
(one attn layer every ``period`` layers — BASELINE config 5's shape) run
as a scan over supersteps of ``[offset mamba] -> attn -> [rest mamba]``,
so trace/compile cost is O(period), not O(n_layer); aperiodic patterns
fall back to a per-layer Python unroll (compile-time bound pinned by
tests/test_model.py).  Per-block ``jax.checkpoint`` implements activation
rematerialization.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mamba_distributed_tpu.config import ModelConfig
from mamba_distributed_tpu.models.attention import (
    attention_mixer,
    attention_mixer_chunk,
    attention_mixer_step,
    attention_page_meta,
    init_attention_params,
    init_attention_state,
    pack_attention_pages,
)
from mamba_distributed_tpu.models.common import init_linear, linear
from mamba_distributed_tpu.models.mamba1 import (
    init_mamba1_params,
    init_mamba1_state,
    mamba1_mixer,
    mamba1_mixer_step,
)
from mamba_distributed_tpu.models.mamba2 import (
    init_mamba2_params,
    init_mamba2_state,
    mamba2_mixer,
    mamba2_mixer_step,
)
from mamba_distributed_tpu.ops.norm import add_rms_norm, rms_norm


def _init_mixer(key: jax.Array, cfg: ModelConfig) -> dict:
    if cfg.ssm_layer == "mamba2":
        return init_mamba2_params(key, cfg)
    if cfg.ssm_layer == "mamba1":
        return init_mamba1_params(key, cfg)
    raise ValueError(cfg.ssm_layer)


def _mixer_fwd(params, cfg, u, seq_ctx=None):
    fn = mamba2_mixer if cfg.ssm_layer == "mamba2" else mamba1_mixer
    return fn(params, cfg, u, seq_ctx=seq_ctx)


def _init_block(key: jax.Array, cfg: ModelConfig, attn: bool) -> dict:
    k_mix, k_mlp = jax.random.split(key)
    p = {
        "norm": {"weight": jnp.ones((cfg.d_model,), jnp.float32)},
        "mixer": init_attention_params(k_mix, cfg) if attn else _init_mixer(k_mix, cfg),
    }
    if cfg.d_intermediate > 0:
        import math

        rescale = (
            1.0 / math.sqrt(2 * cfg.n_layer)
            if cfg.rescale_prenorm_residual else 1.0
        )
        p["norm2"] = {"weight": jnp.ones((cfg.d_model,), jnp.float32)}
        if cfg.moe_num_experts:
            E = cfg.moe_num_experts
            k_r, k_e = jax.random.split(k_mlp)

            def one_expert(k):
                k1, k2 = jax.random.split(k)
                return (
                    init_linear(k1, cfg.d_model, 2 * cfg.d_intermediate,
                                False)["kernel"],
                    init_linear(k2, cfg.d_intermediate, cfg.d_model,
                                False)["kernel"] * rescale,
                )

            w1, w2 = jax.vmap(one_expert)(jax.random.split(k_e, E))
            p["moe"] = {
                "router": init_linear(k_r, cfg.d_model, E, False),
                "w1": w1,  # (E, d, 2*di)
                "w2": w2,  # (E, di, d)
            }
        else:
            k1, k2 = jax.random.split(k_mlp)
            p["mlp"] = {
                "fc1": init_linear(k1, cfg.d_model, 2 * cfg.d_intermediate, False),
                "fc2": init_linear(k2, cfg.d_intermediate, cfg.d_model, False),
            }
            # fc2 is the second residual projection; depth-rescale like out_proj
            p["mlp"]["fc2"]["kernel"] = p["mlp"]["fc2"]["kernel"] * rescale
    return p


def _embed(params: dict, ids: jax.Array, compute_dtype) -> jax.Array:
    """Embedding lookup, transparent to int8 serving quantization
    (ops/quant.py): a quantized embedding is ``{"kernel": int8 (V, d),
    "scale": f32 (V, 1)}`` with one scale per vocab row, so the lookup
    dequantizes just the gathered rows."""
    emb = params["embedding"]
    if isinstance(emb, dict):
        # dequantize in f32 (scales keep full precision — same rule as
        # linear() and _tied_logits), then cast once
        rows = emb["kernel"][ids].astype(jnp.float32) * emb["scale"][ids]
        return rows.astype(compute_dtype)
    return emb[ids].astype(compute_dtype)


def _tied_logits(params: dict, normed: jax.Array, compute_dtype) -> jax.Array:
    """Tied LM head: ``normed @ embedding.T`` with fp32 accumulation.
    A quantized embedding's per-vocab-row scales become per-OUTPUT
    scales of the head matmul — ``(x @ q.T) * scale`` on the fp32
    accumulator, no dequantized weight copy (ops/quant.py)."""
    emb = params["embedding"]
    if isinstance(emb, dict):
        y = jnp.dot(
            normed.astype(compute_dtype),
            emb["kernel"].T.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        return y * emb["scale"][:, 0].astype(jnp.float32)
    return jnp.dot(
        normed.astype(compute_dtype),
        emb.T.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )


def _gated_mlp(params: dict, x: jax.Array, compute_dtype) -> jax.Array:
    """GatedMLP (mamba_ssm modules/mlp.py): fc2(y * silu(gate))."""
    yz = linear(params["fc1"], x, compute_dtype)
    y, gate = jnp.split(yz, 2, axis=-1)
    return linear(params["fc2"], y * jax.nn.silu(gate.astype(jnp.float32)).astype(y.dtype), compute_dtype)


def _moe_mlp(params: dict, cfg: ModelConfig, x: jax.Array, compute_dtype):
    """Token-choice top-k mixture of gated-MLP experts -> (out, aux).

    GShard/Switch-style dense-dispatch formulation, TPU-first: routing,
    capacity assignment, dispatch and combine are all static-shape
    einsums (no gather/scatter, no dynamic shapes), so the MXU runs the
    expert matmuls and GSPMD turns the dispatch/combine contractions
    into all-to-alls when experts are sharded over ``mesh.expert``.
    Tokens over an expert's capacity are dropped (combine weight 0 —
    the residual connection carries them).  ``aux`` is the Switch
    load-balance loss E * sum_e f_e * P_e (== 1 at perfect balance),
    averaged into lm_loss with weight cfg.moe_aux_weight.
    """
    E, k = cfg.moe_num_experts, cfg.moe_top_k
    b, t, d = x.shape
    n = b * t
    cap = max(1, -(-int(cfg.moe_capacity_factor * k * n) // E))

    xt = x.reshape(n, d)
    logits = linear(params["router"], xt, jnp.float32)           # (n, E)
    probs = jax.nn.softmax(logits, axis=-1)

    gate_vals, gate_idx = jax.lax.top_k(probs, k)                # (n, k)
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
    )

    # position of each (choice, token) in its expert's queue — primary
    # choices of every token get capacity before any secondary choice
    oh = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)          # (n, k, E)
    ohf = jnp.swapaxes(oh, 0, 1).reshape(k * n, E)               # priority
    pos_f = jnp.cumsum(ohf, axis=0) - ohf                        # (k*n, E)
    pos = jnp.sum(pos_f * ohf, axis=-1).reshape(k, n).T          # (n, k)
    keep = (pos < cap).astype(gate_vals.dtype)
    gate_vals = gate_vals * keep

    # (n, k, E, C) one-hot over (expert, slot) -> dispatch/combine (n, E, C)
    slot = jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=jnp.float32)
    sel = oh[..., None] * slot[:, :, None, :] * keep[..., None, None]
    dispatch = jnp.sum(sel, axis=1)                              # (n, E, C)
    combine = jnp.sum(sel * gate_vals[..., None, None], axis=1)  # (n, E, C)

    cd = compute_dtype
    xe = jnp.einsum("nd,nec->ecd", xt.astype(cd), dispatch.astype(cd),
                    preferred_element_type=jnp.float32).astype(cd)
    yz = jnp.einsum("ecd,edf->ecf", xe, params["w1"].astype(cd),
                    preferred_element_type=jnp.float32)          # (E,C,2di)
    y, gate = jnp.split(yz, 2, axis=-1)
    h = (y * jax.nn.silu(gate)).astype(cd)
    ye = jnp.einsum("ecf,efd->ecd", h, params["w2"].astype(cd),
                    preferred_element_type=jnp.float32)          # (E, C, d)
    out = jnp.einsum("nec,ecd->nd", combine.astype(jnp.float32), ye)

    # Switch aux: fraction routed to e (over all k choices) x mean prob
    f = jnp.mean(jnp.sum(oh, axis=1), axis=0)                    # (E,)
    P_mean = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(f * P_mean) / k
    return out.reshape(b, t, d).astype(x.dtype), aux


def _block_fwd(block_params, cfg, hidden, residual, attn: bool, seq_ctx=None,
               return_state: bool = False, token_mask=None,
               initial_state=None):
    """One prenorm block: fused add+norm -> mixer [-> add+norm -> MLP/MoE].

    ``return_state=True`` (prefill) additionally returns the mixer's decode
    state (conv+SSM caches, or attention K/V).  ``token_mask`` (prefill
    only) zeroes the mixer's scan inputs at left-pad positions
    (inference/bucketing.py).  ``initial_state`` (chunked prefill) is the
    ``(conv_state, ssm_state)`` carry from the previous chunk for SSM
    mixers, or ``((k_pages, v_pages), page_table, lengths)`` for
    attention mixers — the paged KV cache the chunk writes into
    (lm_prefill_chunk).
    With a MoE model (``cfg.moe_num_experts > 0``) the non-state form
    returns ``(hidden, residual, aux)`` — the layer's load-balance loss
    term.
    """
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    residual_dtype = jnp.float32 if cfg.residual_in_fp32 else compute_dtype
    if hidden is None:
        # single-carry form (lm_forward scans): ``residual`` is already the
        # post-add stream; only the norm remains
        residual = residual.astype(residual_dtype)
        normed = rms_norm(
            residual, block_params["norm"]["weight"], cfg.norm_eps
        ).astype(compute_dtype)
    else:
        normed, residual = add_rms_norm(
            hidden, residual, block_params["norm"]["weight"], cfg.norm_eps,
            residual_dtype=residual_dtype,
        )
    state = None
    if attn:
        if initial_state is not None:
            # chunked prefill: resume against the paged KV cache —
            # initial_state = ((k_pages, v_pages), page_table, lengths);
            # the mask'd pad prefix is handled inside (pad keys are never
            # written to pages, so nothing can attend them)
            kv, page_table, lengths = initial_state
            hidden, state = attention_mixer_chunk(
                block_params["mixer"], cfg, normed, kv, page_table,
                lengths, token_mask=token_mask,
            )
        elif token_mask is not None:
            raise ValueError(
                "token_mask one-shot prefill is SSM-only: full-sequence "
                "attention would attend the pad keys; hybrid bucketed "
                "prompts go through the chunk step instead "
                "(serving/prefill.py)"
            )
        elif return_state:
            hidden, state = attention_mixer(
                block_params["mixer"], cfg, normed, return_final_state=True
            )
        else:
            hidden = attention_mixer(
                block_params["mixer"], cfg, normed, seq_ctx=seq_ctx
            )
    else:
        if return_state:
            mix = mamba2_mixer if cfg.ssm_layer == "mamba2" else mamba1_mixer
            ics, iss = (None, None) if initial_state is None else initial_state
            hidden, state = mix(
                block_params["mixer"], cfg, normed, return_final_state=True,
                token_mask=token_mask,
                initial_conv_state=ics, initial_ssm_state=iss,
            )
        else:
            hidden = _mixer_fwd(block_params["mixer"], cfg, normed, seq_ctx=seq_ctx)
    aux = jnp.zeros((), jnp.float32)
    if cfg.d_intermediate > 0:
        normed, residual = add_rms_norm(
            hidden, residual, block_params["norm2"]["weight"], cfg.norm_eps,
            residual_dtype=jnp.float32 if cfg.residual_in_fp32 else compute_dtype,
        )
        if cfg.moe_num_experts:
            hidden, aux = _moe_mlp(
                block_params["moe"], cfg, normed, compute_dtype
            )
        else:
            hidden = _gated_mlp(block_params["mlp"], normed, compute_dtype)
    if return_state:
        return hidden, residual, state
    if cfg.moe_num_experts:
        return hidden, residual, aux
    return hidden, residual


def _final_norm(params, cfg: ModelConfig, hidden, residual):
    """Final (fused add+)norm of the stream.  ``hidden=None`` means
    ``residual`` is already the post-add stream (single-carry form) and
    only the norm is applied.  Shared by _final_logits and the blocked-CE
    loss path so their numerics cannot diverge."""
    residual_dtype = (
        jnp.float32 if cfg.residual_in_fp32 else jnp.dtype(cfg.compute_dtype)
    )
    if hidden is None:
        return rms_norm(
            residual.astype(residual_dtype), params["norm_f"]["weight"],
            cfg.norm_eps,
        )
    normed, _ = add_rms_norm(
        hidden, residual, params["norm_f"]["weight"], cfg.norm_eps,
        residual_dtype=residual_dtype,
    )
    return normed


def _head_matrix(params, cfg: ModelConfig):
    """(V, d) LM-head matrix: the tied embedding, or the lm_head kernel
    transposed (bias-free by construction — init_lm_params builds it with
    ``init_linear(..., bias=False)``)."""
    if cfg.tie_embeddings:
        return params["embedding"]
    if "bias" in params["lm_head"]:  # not an assert: must survive python -O
        raise ValueError(
            "blocked CE assumes a bias-free lm_head; a bias would be "
            "silently ignored, training against a wrong loss"
        )
    return params["lm_head"]["kernel"].T


def _final_logits(params, cfg: ModelConfig, hidden, residual):
    """Final fused add+norm -> (tied) LM head, fp32-accumulated."""
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    normed = _final_norm(params, cfg, hidden, residual)
    if cfg.tie_embeddings:
        return _tied_logits(params, normed, compute_dtype)
    return linear(params["lm_head"], normed, compute_dtype).astype(jnp.float32)


def _remat(fn, cfg: ModelConfig, static_argnums=()):
    """Per-block checkpointing with the configured save policy."""
    if cfg.remat_policy == "dots":
        policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    elif cfg.remat_policy == "mixer":
        # save the scan/attention outputs (~12-25 MB/layer bf16) so the
        # backward recomputes only the projections/conv/norms, never the
        # SSD chunked scan itself
        policy = jax.checkpoint_policies.save_only_these_names("mixer_out")
    else:
        policy = None
    return jax.checkpoint(fn, policy=policy, static_argnums=static_argnums)


def _hybrid_period(cfg: ModelConfig):
    """Detect a periodic hybrid pattern.

    Returns (period, offset) when ``attn_layer_idx`` is exactly one
    attention layer per ``period = n_layer / n_attn`` layers at a fixed
    in-period ``offset`` (config 5: every 8th layer at offset 3); None
    for aperiodic patterns (which take the unrolled path).
    """
    idx = cfg.attn_layer_idx
    n_attn = len(idx)
    if n_attn == 0 or cfg.n_layer % n_attn:
        return None
    p = cfg.n_layer // n_attn
    r = idx[0]
    if not 0 <= r < p:
        return None
    if tuple(idx) != tuple(r + g * p for g in range(n_attn)):
        return None
    return p, r


def _group_mamba_stack(params, cfg: ModelConfig, period: int):
    """(n_mamba, ...) stacked mamba blocks -> (n_attn, period-1, ...)."""
    n_groups = len(cfg.attn_layer_idx)
    return jax.tree.map(
        lambda x: x.reshape((n_groups, period - 1) + x.shape[1:]),
        params["blocks"],
    )


def init_lm_params(key: jax.Array, cfg: ModelConfig) -> dict:
    """Build the full parameter pytree (fp32 master weights)."""
    n = cfg.n_layer
    attn_idx = set(cfg.attn_layer_idx)
    k_emb, k_blocks, k_head = jax.random.split(key, 3)
    layer_keys = jax.random.split(k_blocks, n)

    params = {
        "embedding": cfg.initializer_range
        * jax.random.normal(k_emb, (cfg.vocab_size_padded, cfg.d_model), jnp.float32),
        "norm_f": {"weight": jnp.ones((cfg.d_model,), jnp.float32)},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = init_linear(k_head, cfg.d_model, cfg.vocab_size_padded, False)

    if attn_idx:
        mamba_keys = [layer_keys[i] for i in range(n) if i not in attn_idx]
        attn_keys = [layer_keys[i] for i in range(n) if i in attn_idx]
        params["blocks"] = jax.vmap(lambda k: _init_block(k, cfg, False))(
            jnp.stack(mamba_keys)
        )
        params["attn_blocks"] = jax.vmap(lambda k: _init_block(k, cfg, True))(
            jnp.stack(attn_keys)
        )
    else:
        params["blocks"] = jax.vmap(lambda k: _init_block(k, cfg, False))(layer_keys)
    return params


def _backbone(
    params: dict,
    cfg: ModelConfig,
    input_ids: jax.Array,
    num_last_tokens: int = 0,
    seq_ctx=None,
):
    """Embedding -> layer stack.  Returns (post-add fp32 stream, aux sum) —
    everything before the final norm + LM head (shared by lm_forward and
    the blocked-CE loss path)."""
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    residual_dtype = jnp.float32 if cfg.residual_in_fp32 else compute_dtype
    hidden = _embed(params, input_ids, compute_dtype)
    # Single-carry form: the layer loop carries ONE post-add fp32 stream
    # instead of the (hidden, residual) pair.  The pair made every remat
    # boundary save the stream twice — stacked bf16 AND fp32 copies per
    # layer, ~2.4 GB of saves on the 280M recipe (round-4 trace); the
    # fp32 add chain and every norm input are bit-identical either way.
    res = hidden.astype(residual_dtype)
    moe = cfg.moe_num_experts > 0
    aux_total = jnp.zeros((), jnp.float32)

    def block(bp, cfg_, res_, attn, sc):
        """post-add stream -> (new stream, aux) — uniform carry shape."""
        out = _block_fwd(bp, cfg_, None, res_, attn, sc)
        if moe:
            h, rs, a = out
        else:
            (h, rs), a = out, jnp.zeros((), jnp.float32)
        return rs + h.astype(rs.dtype), a

    if cfg.attn_layer_idx and (per := _hybrid_period(cfg)) is not None:
        # periodic hybrid: scan over supersteps — trace cost O(period)
        p, r = per
        mstack = _group_mamba_stack(params, cfg, p)

        def mbody(carry, bp):
            rs, ax = carry
            rs, a = block(bp, cfg, rs, False, seq_ctx)
            return (rs, ax + a), None

        def abody_(bp, cfg_, rs, ax, attn, sc):
            rs, a = block(bp, cfg_, rs, attn, sc)
            return rs, ax + a

        abody = abody_
        if cfg.remat:
            mbody = _remat(mbody, cfg)
            abody = _remat(abody, cfg, static_argnums=(1, 4, 5))

        def group(carry, xs):
            mblk, ablk = xs
            carry, _ = jax.lax.scan(
                mbody, carry, jax.tree.map(lambda x: x[:r], mblk)
            )
            carry = abody(ablk, cfg, *carry, True, seq_ctx)
            carry, _ = jax.lax.scan(
                mbody, carry, jax.tree.map(lambda x: x[r:], mblk)
            )
            return carry, None

        (res, aux_total), _ = jax.lax.scan(
            group, (res, aux_total), (mstack, params["attn_blocks"])
        )
    elif cfg.attn_layer_idx:
        attn_idx = set(cfg.attn_layer_idx)
        mi = ai = 0
        for i in range(cfg.n_layer):
            attn = i in attn_idx
            stack = params["attn_blocks"] if attn else params["blocks"]
            j = ai if attn else mi
            bp = jax.tree.map(lambda p, j=j: p[j], stack)
            body = block
            if cfg.remat:
                body = _remat(body, cfg, static_argnums=(1, 3, 4))
            res, a = body(bp, cfg, res, attn, seq_ctx)
            aux_total = aux_total + a
            if attn:
                ai += 1
            else:
                mi += 1
    else:
        if moe:
            def body(carry, bp):
                rs, ax = carry
                rs, a = block(bp, cfg, rs, False, seq_ctx)
                return (rs, ax + a), None

            if cfg.remat:
                body = _remat(body, cfg)
            (res, aux_total), _ = jax.lax.scan(
                body, (res, aux_total), params["blocks"]
            )
        else:
            def body(rs, bp):
                rs, _ = block(bp, cfg, rs, False, seq_ctx)
                return rs, None

            if cfg.remat:
                body = _remat(body, cfg)
            res, _ = jax.lax.scan(body, res, params["blocks"])

    if num_last_tokens > 0:
        res = res[:, -num_last_tokens:]
    return res, aux_total


def lm_forward(
    params: dict,
    cfg: ModelConfig,
    input_ids: jax.Array,
    num_last_tokens: int = 0,
    seq_ctx=None,
    return_aux: bool = False,
):
    """input_ids (b, t) int32 -> logits (b, t[, num_last_tokens], V) bf16.

    ``return_aux=True`` additionally returns the per-MoE-layer mean of
    the load-balance aux loss (0.0 for dense models) — what lm_loss
    folds in with weight ``cfg.moe_aux_weight``.
    """
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    res, aux_total = _backbone(params, cfg, input_ids, num_last_tokens, seq_ctx)
    logits = _final_logits(params, cfg, None, res).astype(compute_dtype)
    if return_aux:
        n_moe = cfg.n_layer if cfg.moe_num_experts else 1
        return logits, aux_total / n_moe
    return logits


def lm_loss(
    params: dict,
    cfg: ModelConfig,
    input_ids: jax.Array,
    targets: jax.Array,
    seq_ctx=None,
) -> jax.Array:
    """Mean cross-entropy in fp32 (reference model.py:43-46; targets are the
    loader's pre-shifted next tokens, so no internal shift).

    Formulated as ``logsumexp - gathered logit`` rather than materializing
    ``log_softmax`` — the dense (b, t, V) fp32 log-prob tensor (1.6 GB at
    the 280M recipe) never exists; only the two reductions over V do.

    ``cfg.loss_impl="blocked"`` goes further: the LM-head matmul runs
    vocab-block-by-block under an online logsumexp (ops/loss.py), so even
    the (b, t, V) *bf16 logits* tensor (824 MB at the 280M recipe, 3.3 GB
    at the reference's B=32) never exists — forward or backward.
    """
    if cfg.loss_impl == "blocked":
        from mamba_distributed_tpu.ops.loss import blocked_cross_entropy

        res, aux = _backbone(params, cfg, input_ids, seq_ctx=seq_ctx)
        ce = blocked_cross_entropy(
            _final_norm(params, cfg, None, res),
            _head_matrix(params, cfg),
            targets,
            n_blocks=cfg.loss_vocab_blocks,
            compute_dtype=jnp.dtype(cfg.compute_dtype),
        )
        aux = aux / (cfg.n_layer if cfg.moe_num_experts else 1)
    else:
        logits, aux = lm_forward(
            params, cfg, input_ids, seq_ctx=seq_ctx, return_aux=True
        )
        lf = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(lf, axis=-1)
        tgt = jnp.take_along_axis(lf, targets[..., None], axis=-1)[..., 0]
        ce = jnp.mean(lse - tgt)
    if cfg.moe_num_experts:
        return ce + cfg.moe_aux_weight * aux
    return ce


def lm_loss_pipelined(
    params: dict,
    cfg: ModelConfig,
    input_ids: jax.Array,
    targets: jax.Array,
    mesh,
    axis: str = "pipe",
    batch_axes=None,
) -> jax.Array:
    """``lm_loss`` averaged over grad-accum microbatches, with the layer
    stack pipelined over the mesh's ``axis`` (GPipe).

    The grad-accum microbatches ARE the pipeline microbatches:
    input_ids/targets carry a leading (accum, B, T) axis, embedding and
    LM head run batched over it, and the block stack streams the
    microbatches through ``parallel/pipeline.pipelined_layers`` — whose
    schedule is differentiable (ppermute/scan/where all transpose), so
    one ``jax.grad`` trains through the pipeline.  Uniform stacks
    pipeline per layer; periodic hybrids (config-5 pattern) pipeline per
    *superstep* — each pipeline "layer" is one
    ``[offset mamba] -> attn -> [rest mamba]`` group, so the per-stage
    work stays homogeneous.
    """
    from mamba_distributed_tpu.parallel.pipeline import pipelined_layers

    compute_dtype = jnp.dtype(cfg.compute_dtype)
    residual_dtype = jnp.float32 if cfg.residual_in_fp32 else compute_dtype
    hidden = _embed(params, input_ids, compute_dtype)  # (mb,b,t,d)
    # single-carry post-add stream (see lm_forward)
    res = hidden.astype(residual_dtype)

    def sc_block(bp, res_, attn):
        h, rs = _block_fwd(bp, cfg, None, res_, attn)
        return rs + h.astype(rs.dtype)

    if cfg.attn_layer_idx:
        per = _hybrid_period(cfg)
        assert per is not None, (
            "pipeline parallelism needs a uniform stack or a periodic hybrid"
        )
        p, r = per
        stacked = (_group_mamba_stack(params, cfg, p), params["attn_blocks"])

        def mbody(carry, bp):
            return sc_block(bp, carry, False), None

        def body(carry, group):
            mblk, ablk = group
            carry, _ = jax.lax.scan(
                mbody, carry, jax.tree.map(lambda x: x[:r], mblk)
            )
            carry = sc_block(ablk, carry, True)
            carry, _ = jax.lax.scan(
                mbody, carry, jax.tree.map(lambda x: x[r:], mblk)
            )
            return carry
    else:
        stacked = params["blocks"]

        def body(carry, bp):
            return sc_block(bp, carry, False)

    if cfg.remat:
        body = _remat(body, cfg)
    res = pipelined_layers(
        body, stacked, res, mesh, axis=axis,
        batch_axes=batch_axes,
    )
    lf = _final_logits(params, cfg, None, res)
    lse = jax.nn.logsumexp(lf, axis=-1)
    tgt = jnp.take_along_axis(lf, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - tgt)


def count_params(params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))


# ---------------------------------------------------------------------------
# Recurrent decode (O(1) per token) — used by inference/generate.py
# ---------------------------------------------------------------------------


def lm_prefill(params: dict, cfg: ModelConfig, input_ids: jax.Array,
               max_len: int = 0, token_mask: jax.Array | None = None):
    """Parallel prefill: one full-sequence forward that also returns the
    per-layer decode state (conv cache, SSM state, attention KV caches
    padded to ``max_len``).  The sequential per-token prefill this replaces
    is what the reference effectively did by re-running the prefix
    (SURVEY.md §3.3).  Shares ``_block_fwd`` with lm_forward.

    ``token_mask`` (b, t) {0,1} marks LEFT-padded bucketed prompts
    (inference/bucketing.py): pad positions contribute nothing to the
    conv/SSM state, so the returned state matches the unpadded
    prefill's — the conv cache bit-exactly, the SSM state up to
    chunk-regrouping rounding (~1e-7 fp32).  Pure-SSM stacks only —
    attention layers reject it (_block_fwd).

    Returns (last_logits (b, V) fp32, state) — state feeds lm_step.
    """
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    b, t = input_ids.shape
    if cfg.attn_layer_idx and max_len <= t:
        raise ValueError(
            f"hybrid prefill needs KV capacity beyond the prompt: "
            f"max_len={max_len} <= prompt length {t}"
        )
    hidden = _embed(params, input_ids, compute_dtype)
    residual = None

    def to_pages(state):
        # raw full-sequence (k, v) -> identity-paged decode cache with
        # ``max_len`` capacity (the shared page_table/lengths meta is
        # attached once, below)
        k, v = state
        return pack_attention_pages(cfg, k, v, max_len)

    if cfg.attn_layer_idx and token_mask is not None:
        raise ValueError(
            "token_mask prefill is SSM-only (full-sequence attention would "
            "attend the pad keys); hybrid bucketed prompts go through the "
            "chunk step (serving/prefill.py) instead"
        )

    if cfg.attn_layer_idx and (per := _hybrid_period(cfg)) is not None:
        # periodic hybrid: superstep scan mirroring lm_forward's
        p, r = per
        residual = jnp.zeros_like(
            hidden, dtype=jnp.float32 if cfg.residual_in_fp32 else compute_dtype
        )
        mstack = _group_mamba_stack(params, cfg, p)

        def mbody(carry, bp):
            h, rs = carry
            h, rs, st = _block_fwd(bp, cfg, h, rs, False, return_state=True)
            return (h, rs), st

        def group(carry, xs):
            mblk, ablk = xs
            carry, st_pre = jax.lax.scan(
                mbody, carry, jax.tree.map(lambda x: x[:r], mblk)
            )
            hidden, residual, a_st = _block_fwd(
                ablk, cfg, *carry, True, return_state=True
            )
            carry, st_post = jax.lax.scan(
                mbody, (hidden, residual), jax.tree.map(lambda x: x[r:], mblk)
            )
            m_st = jax.tree.map(
                lambda a, b: jnp.concatenate([a, b], axis=0), st_pre, st_post
            )
            return carry, (m_st, to_pages(a_st))

        (hidden, residual), (m_states, a_states) = jax.lax.scan(
            group, (hidden, residual), (mstack, params["attn_blocks"])
        )
        state = {
            # (n_attn, period-1, ...) -> (n_mamba, ...), global layer order
            "blocks": jax.tree.map(
                lambda x: x.reshape((-1,) + x.shape[2:]), m_states
            ),
            "attn_blocks": a_states,
            "attn_meta": (
                attention_page_meta(cfg, b, max_len)[0],
                jnp.full((b,), t, jnp.int32),
            ),
        }
    elif cfg.attn_layer_idx:
        attn_idx = set(cfg.attn_layer_idx)
        mi = ai = 0
        m_states, a_states = [], []
        for i in range(cfg.n_layer):
            attn = i in attn_idx
            stack = params["attn_blocks"] if attn else params["blocks"]
            bp = jax.tree.map(lambda p, j=(ai if attn else mi): p[j], stack)
            hidden, residual, st = _block_fwd(
                bp, cfg, hidden, residual, attn, return_state=True
            )
            if attn:
                a_states.append(to_pages(st))
                ai += 1
            else:
                m_states.append(st)
                mi += 1
        stack = lambda sts: jax.tree.map(lambda *xs: jnp.stack(xs), *sts)
        state = {
            "blocks": stack(m_states),
            "attn_blocks": stack(a_states),
            "attn_meta": (
                attention_page_meta(cfg, b, max_len)[0],
                jnp.full((b,), t, jnp.int32),
            ),
        }
    else:
        residual = jnp.zeros_like(
            hidden, dtype=jnp.float32 if cfg.residual_in_fp32 else compute_dtype
        )

        def body(carry, bp):
            hidden, residual = carry
            hidden, residual, st = _block_fwd(
                bp, cfg, hidden, residual, False, return_state=True,
                token_mask=token_mask,
            )
            return (hidden, residual), st

        (hidden, residual), state_blocks = jax.lax.scan(
            body, (hidden, residual), params["blocks"]
        )
        state = {"blocks": state_blocks}

    logits = _final_logits(params, cfg, hidden[:, -1:], residual[:, -1:])
    return logits[:, 0].astype(jnp.float32), state


def lm_prefill_chunk(params: dict, cfg: ModelConfig, input_ids: jax.Array,
                     state, token_mask: jax.Array | None = None):
    """Resumable prefill: one chunk of a prompt, carries threaded through.

    The chunked-prefill workhorse (serving/prefill.py): identical to the
    pure-SSM branch of ``lm_prefill`` except every layer's mixer starts
    from ``state`` — the ``{"blocks": (conv (L, b, ...), ssm (L, b, ...))}``
    pytree a previous chunk (or ``init_lm_state``) produced — so a long
    prompt runs as a sequence of fixed-shape chunk calls: one compiled
    shape total, and the serving engine can interleave chunks with
    decode ticks.

    Chunk-split equivalence vs one ``lm_prefill`` over the concatenated
    sequence: everything outside the mixers is per-position; the conv
    carry is the literal trailing inputs (bit-exact across a split); the
    SSM carry enters the next chunk's state passing as mathematically
    the same recurrence with re-associated fp32 sums (~1e-6 — same
    class of noise as the pow2 bucketing's pad-shifted chunk boundaries;
    tests/test_prefill.py pins both the exact and the tolerance parts).
    Exact token parity between the engine and ``generate()`` therefore
    comes from both sides running THIS function over identical chunks,
    not from chunked == one-shot.

    Hybrid stacks resume attention layers against the PAGED KV cache in
    ``state["attn_blocks"]``/``state["attn_meta"]`` — each chunk writes
    its real tokens' K/V into the row's pages at [lengths, lengths +
    n_real) and attends over the page view (models/attention.
    attention_mixer_chunk), so a hybrid prompt's pages fill as chunks
    land and the serving engine can interleave them with decode ticks.

    Returns (last_logits (b, V) fp32, new state) — same contract as
    ``lm_prefill``.
    """
    hidden, residual, new_state = _chunk_backbone(
        params, cfg, input_ids, state, token_mask
    )
    logits = _final_logits(params, cfg, hidden[:, -1:], residual[:, -1:])
    return logits[:, 0].astype(jnp.float32), new_state


def lm_verify_chunk(params: dict, cfg: ModelConfig, input_ids: jax.Array,
                    state, token_mask: jax.Array | None = None):
    """Speculative-decoding VERIFY step: the chunk machinery of
    ``lm_prefill_chunk`` (identical carry threading, identical paged KV
    chunk write for hybrids) but returning the logits of EVERY position
    — ``(logits (b, c, V) fp32, new state)`` where ``logits[:, i]``
    scores the token AFTER ``input_ids[:, i]``.

    This is the whole trick (serving/spec_decode.py): one launch reads
    the weights ONCE and prices all ``c = K+1`` positions of a drafted
    continuation, where the decode tick would pay one full weight read
    per token.  The caller compares ``argmax(logits[:, i-1])`` against
    the fed draft at ``i`` to find the longest correct prefix, commits
    it, and rolls back the carries on a rejection (the returned state
    reflects ALL ``c`` fed tokens, so it is only committable when every
    one of them verified — the pending-token scheme in
    serving/spec_decode.py keeps that an all-or-nothing choice).

    Hybrid note: the chunk's K/V page writes land at ``[lengths,
    lengths + n_real)`` exactly like a prefill chunk; on rollback the
    caller simply does not advance its ``lengths`` mirror, so the
    written cells are dead-by-``lengths`` and the next verify rewrites
    them — the same invariant the ragged kernels already honor for
    masked rows."""
    hidden, residual, new_state = _chunk_backbone(
        params, cfg, input_ids, state, token_mask
    )
    logits = _final_logits(params, cfg, hidden, residual)
    return logits.astype(jnp.float32), new_state


def _chunk_backbone(params: dict, cfg: ModelConfig, input_ids: jax.Array,
                    state, token_mask: jax.Array | None = None):
    """Shared body of ``lm_prefill_chunk``/``lm_verify_chunk``: embed ->
    carry-threaded layer stack -> (hidden, residual, new state).  One
    implementation so the prefill and verify paths cannot diverge."""
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    hidden = _embed(params, input_ids, compute_dtype)
    residual = jnp.zeros_like(
        hidden, dtype=jnp.float32 if cfg.residual_in_fp32 else compute_dtype
    )

    def body(carry, xs):
        hidden, residual = carry
        bp, st = xs
        hidden, residual, new_st = _block_fwd(
            bp, cfg, hidden, residual, False, return_state=True,
            token_mask=token_mask, initial_state=st,
        )
        return (hidden, residual), new_st

    if cfg.attn_layer_idx:
        tbl, lengths = state["attn_meta"]
        b, c = input_ids.shape
        if token_mask is None:
            n_real = jnp.full((b,), c, jnp.int32)
        else:
            n_real = jnp.sum(
                (token_mask > 0.5).astype(jnp.int32), axis=1
            )

        def abody(ablk, h, rs, akv):
            return _block_fwd(
                ablk, cfg, h, rs, True, return_state=True,
                token_mask=token_mask,
                initial_state=(akv, tbl, lengths),
            )

        if (per := _hybrid_period(cfg)) is not None:
            p, r = per
            n_attn = len(cfg.attn_layer_idx)
            mstack = _group_mamba_stack(params, cfg, p)
            mstate = jax.tree.map(
                lambda s: s.reshape((n_attn, p - 1) + s.shape[1:]),
                state["blocks"],
            )

            def group(carry, xs):
                mblk, ablk, mst, akv = xs
                pre = lambda x: jax.tree.map(lambda v: v[:r], x)
                post = lambda x: jax.tree.map(lambda v: v[r:], x)
                carry, new_pre = jax.lax.scan(
                    body, carry, (pre(mblk), pre(mst))
                )
                hidden, residual, new_kv = abody(ablk, *carry, akv)
                carry, new_post = jax.lax.scan(
                    body, (hidden, residual), (post(mblk), post(mst))
                )
                new_m = jax.tree.map(
                    lambda a, b: jnp.concatenate([a, b], axis=0),
                    new_pre, new_post,
                )
                return carry, (new_m, new_kv)

            (hidden, residual), (new_m, new_a) = jax.lax.scan(
                group, (hidden, residual),
                (mstack, params["attn_blocks"], mstate,
                 state["attn_blocks"]),
            )
            new_blocks = jax.tree.map(
                lambda x: x.reshape((-1,) + x.shape[2:]), new_m
            )
        else:
            attn_idx = set(cfg.attn_layer_idx)
            mi = ai = 0
            new_ms, new_as = [], []
            for i in range(cfg.n_layer):
                attn = i in attn_idx
                if attn:
                    bp = jax.tree.map(
                        lambda p_, j=ai: p_[j], params["attn_blocks"]
                    )
                    akv = jax.tree.map(
                        lambda s, j=ai: s[j], state["attn_blocks"]
                    )
                    hidden, residual, st = abody(bp, hidden, residual, akv)
                    new_as.append(st)
                    ai += 1
                else:
                    bp = jax.tree.map(
                        lambda p_, j=mi: p_[j], params["blocks"]
                    )
                    st = jax.tree.map(
                        lambda s, j=mi: s[j], state["blocks"]
                    )
                    hidden, residual, st = _block_fwd(
                        bp, cfg, hidden, residual, False,
                        return_state=True, token_mask=token_mask,
                        initial_state=st,
                    )
                    new_ms.append(st)
                    mi += 1
            stack = lambda sts: jax.tree.map(
                lambda *xs: jnp.stack(xs), *sts
            )
            new_blocks, new_a = stack(new_ms), stack(new_as)
        return hidden, residual, {
            "blocks": new_blocks,
            "attn_blocks": new_a,
            "attn_meta": (tbl, lengths + n_real),
        }

    (hidden, residual), state_blocks = jax.lax.scan(
        body, (hidden, residual), (params["blocks"], state["blocks"])
    )
    return hidden, residual, {"blocks": state_blocks}


def init_lm_blocks_state(cfg: ModelConfig, batch: int):
    """Layer-stacked conv+SSM decode states for the MAMBA layers only —
    what the serving slot pool's per-slot writes cover (the paged
    attention KV lives in the shared page pool, not per-slot rows)."""
    init_mix = init_mamba2_state if cfg.ssm_layer == "mamba2" else init_mamba1_state
    n = cfg.n_layer - len(cfg.attn_layer_idx)
    cs, ss = init_mix(cfg, batch)
    return (
        jnp.tile(cs[None], (n,) + (1,) * cs.ndim),
        jnp.tile(ss[None], (n,) + (1,) * ss.ndim),
    )


def init_lm_state(cfg: ModelConfig, batch: int, max_len: int = 0):
    """Per-layer decode states, layer-stacked to mirror the param layout.

    Hybrid stacks additionally carry the paged attention KV cache:
    per-layer page pools under ``"attn_blocks"`` plus the layer-shared
    ``"attn_meta" = (page_table (b, W), lengths (b,))`` (every attention
    layer caches the same positions, so one table serves them all).
    ``max_len`` sizes the per-row page budget."""
    if cfg.attn_layer_idx:
        n_attn = len(cfg.attn_layer_idx)
        attn_states = [
            init_attention_state(cfg, batch, max_len) for _ in range(n_attn)
        ]
        stack = lambda states: jax.tree.map(lambda *xs: jnp.stack(xs), *states)
        return {
            "blocks": init_lm_blocks_state(cfg, batch),
            "attn_blocks": stack(attn_states),
            "attn_meta": attention_page_meta(cfg, batch, max_len),
        }
    return {"blocks": init_lm_blocks_state(cfg, batch)}


def _block_step(bp, cfg: ModelConfig, hidden, residual, st, attn: bool,
                attn_ctx=None):
    """One decode-step block (shared by the scan and unrolled paths).
    ``attn_ctx = (page_table, lengths, write_mask)`` is the layer-shared
    paged-KV metadata (attention layers only)."""
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    normed, residual = add_rms_norm(
        hidden, residual, bp["norm"]["weight"], cfg.norm_eps,
    )
    if attn:
        page_table, lengths, write_mask = attn_ctx
        hidden, st = attention_mixer_step(
            bp["mixer"], cfg, normed, st, page_table, lengths,
            write_mask=write_mask,
        )
    else:
        mix_step = (
            mamba2_mixer_step if cfg.ssm_layer == "mamba2" else mamba1_mixer_step
        )
        hidden, st = mix_step(bp["mixer"], cfg, normed, *st)
    if cfg.d_intermediate > 0:
        normed, residual = add_rms_norm(
            hidden, residual, bp["norm2"]["weight"], cfg.norm_eps,
        )
        if cfg.moe_num_experts:
            hidden, _ = _moe_mlp(
                bp["moe"], cfg, normed[:, None, :], compute_dtype
            )
            hidden = hidden[:, 0]
        else:
            hidden = _gated_mlp(bp["mlp"], normed, compute_dtype)
    return hidden, residual, st


def lm_step(params: dict, cfg: ModelConfig, state, token: jax.Array,
            write_mask: jax.Array | None = None, pipeline=None):
    """One decode step.  token (b,) int32 -> (logits (b, V), new state).

    ``write_mask`` (b,) bool (hybrid stacks only) marks rows whose paged
    attention KV may be written this step; masked rows' writes land in
    the trash page and their ``lengths`` freeze — how the serving tick
    keeps dead/empty/prefilling slots from touching live pages while
    still computing the whole batch in one trace.  ``None`` (generate's
    decode loop) writes every row.

    ``pipeline`` (pure-SSM stacks only) is ``(mesh, n_micro)``: the
    layer scan runs as a GPipe-microbatched schedule over the 3-D
    serving mesh's ``stage`` axis instead of a local ``lax.scan`` —
    ``n_micro`` contiguous lane blocks of the batch flow through the
    stage-resident layer groups with ppermute handoffs
    (parallel/pipeline.pipelined_decode_layers; the serving tick's
    microbatched launch).  Bitwise identical to ``pipeline=None``:
    each lane's per-layer op sequence is unchanged, only the
    (layer-group, lane-block) execution order moves.  ``None`` (every
    non-pipelined caller) is the exact status quo.
    """
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    hidden = _embed(params, token, compute_dtype)
    residual = None

    def mbody(carry, xs):
        h, rs = carry
        bp, st = xs
        h, rs, st = _block_step(bp, cfg, h, rs, st, False)
        return (h, rs), st

    if cfg.attn_layer_idx:
        tbl, lengths = state["attn_meta"]
        attn_ctx = (tbl, lengths, write_mask)
        adv = (
            jnp.ones_like(lengths) if write_mask is None
            else write_mask.astype(lengths.dtype)
        )
        new_meta = (tbl, lengths + adv)

    if cfg.attn_layer_idx and (per := _hybrid_period(cfg)) is not None:
        p, r = per
        residual = jnp.zeros_like(hidden, dtype=jnp.float32)
        mstack = _group_mamba_stack(params, cfg, p)
        mstate = jax.tree.map(
            lambda s: s.reshape((len(cfg.attn_layer_idx), p - 1) + s.shape[1:]),
            state["blocks"],
        )

        def group(carry, xs):
            mblk, ablk, mst, ast = xs
            pre = lambda x: jax.tree.map(lambda v: v[:r], x)
            post = lambda x: jax.tree.map(lambda v: v[r:], x)
            carry, new_pre = jax.lax.scan(mbody, carry, (pre(mblk), pre(mst)))
            hidden, residual, ast = _block_step(
                ablk, cfg, *carry, ast, True, attn_ctx=attn_ctx
            )
            carry, new_post = jax.lax.scan(
                mbody, (hidden, residual), (post(mblk), post(mst))
            )
            new_m = jax.tree.map(
                lambda a, b: jnp.concatenate([a, b], axis=0), new_pre, new_post
            )
            return carry, (new_m, ast)

        (hidden, residual), (new_m, new_a) = jax.lax.scan(
            group, (hidden, residual),
            (mstack, params["attn_blocks"], mstate, state["attn_blocks"]),
        )
        new_state = {
            "blocks": jax.tree.map(
                lambda x: x.reshape((-1,) + x.shape[2:]), new_m
            ),
            "attn_blocks": new_a,
            "attn_meta": new_meta,
        }
    elif cfg.attn_layer_idx:
        attn_idx = set(cfg.attn_layer_idx)
        mi = ai = 0
        new_m, new_a = [], []
        for i in range(cfg.n_layer):
            attn = i in attn_idx
            if attn:
                bp = jax.tree.map(lambda p, j=ai: p[j], params["attn_blocks"])
                st = jax.tree.map(lambda s, j=ai: s[j], state["attn_blocks"])
            else:
                bp = jax.tree.map(lambda p, j=mi: p[j], params["blocks"])
                st = jax.tree.map(lambda s, j=mi: s[j], state["blocks"])
            hidden, residual, st = _block_step(
                bp, cfg, hidden, residual, st, attn,
                attn_ctx=attn_ctx if attn else None,
            )
            if attn:
                new_a.append(st)
                ai += 1
            else:
                new_m.append(st)
                mi += 1
        stack = lambda states: jax.tree.map(lambda *xs: jnp.stack(xs), *states)
        new_state = {
            "blocks": stack(new_m),
            "attn_blocks": stack(new_a),
            "attn_meta": new_meta,
        }
    else:
        residual = jnp.zeros_like(hidden, dtype=jnp.float32)
        if pipeline is not None:
            from mamba_distributed_tpu.parallel.pipeline import (
                pipelined_decode_layers,
            )

            mesh, n_micro = pipeline

            def pbody(act, bp, st):
                h, rs = act
                h, rs, st = _block_step(bp, cfg, h, rs, st, False)
                return (h, rs), st

            (hidden, residual), new_blocks = pipelined_decode_layers(
                pbody, params["blocks"], state["blocks"],
                (hidden, residual), mesh, n_micro=n_micro,
            )
        else:
            (hidden, residual), new_blocks = jax.lax.scan(
                mbody, (hidden, residual), (params["blocks"], state["blocks"])
            )
        new_state = {"blocks": new_blocks}

    normed, _ = add_rms_norm(hidden, residual, params["norm_f"]["weight"], cfg.norm_eps)
    if cfg.tie_embeddings:
        logits = _tied_logits(params, normed, compute_dtype)
    else:
        logits = linear(params["lm_head"], normed, compute_dtype).astype(jnp.float32)
    return logits.astype(jnp.float32), new_state
