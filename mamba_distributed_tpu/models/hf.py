"""HF / mamba_ssm checkpoint importer.

Equivalent of the reference's ``load_from_hf`` (/root/reference/model.py:
97-116), for a zero-egress environment: instead of ``cached_file`` hub
downloads, it maps a *local* ``state-spaces``-style torch state dict
(``MambaLMHeadModel`` naming: ``backbone.layers.{i}.mixer...``) onto this
framework's layer-stacked JAX param tree.

Layout differences handled here:
  * torch Linear stores (out, in) -> ours is (in, out): transpose
  * torch depthwise Conv1d stores (ch, 1, width) -> ours (ch, width)
  * per-layer tensors -> stacked along a leading n_layer axis
  * tied lm_head.weight is dropped (ours reuses the embedding)
"""

from __future__ import annotations

import json
import os

import jax.numpy as jnp
import numpy as np

from mamba_distributed_tpu.config import ModelConfig


def config_from_hf_json(config_data: dict) -> ModelConfig:
    """mamba_ssm MambaConfig json -> ModelConfig."""
    ssm_cfg = config_data.get("ssm_cfg") or {}
    layer = ssm_cfg.get("layer", "Mamba1").lower()
    kw = dict(
        d_model=config_data["d_model"],
        n_layer=config_data["n_layer"],
        vocab_size=config_data["vocab_size"],
        ssm_layer="mamba2" if layer == "mamba2" else "mamba1",
        d_intermediate=config_data.get("d_intermediate", 0),
        rms_norm=config_data.get("rms_norm", True),
        residual_in_fp32=config_data.get("residual_in_fp32", True),
        tie_embeddings=config_data.get("tie_embeddings", True),
        pad_vocab_size_multiple=config_data.get("pad_vocab_size_multiple", 8),
    )
    for src, dst in [
        ("d_state", "d_state"), ("d_conv", "d_conv"), ("expand", "expand"),
        ("headdim", "headdim"), ("ngroups", "ngroups"),
        ("chunk_size", "chunk_size"),
    ]:
        if src in ssm_cfg:
            kw[dst] = ssm_cfg[src]
    # hybrid (Jamba-style): MambaConfig.attn_layer_idx + attn_cfg
    # (mamba_ssm MHA naming: num_heads / num_heads_kv / head_dim /
    # rotary_emb_dim — whose default 0 means NO rotary, matching our
    # attn_rotary_dim=0; our "full head dim" is -1)
    attn_idx = config_data.get("attn_layer_idx") or []
    if attn_idx:
        attn_cfg = config_data.get("attn_cfg") or {}
        kw["attn_layer_idx"] = tuple(attn_idx)
        if "num_heads" in attn_cfg:
            kw["attn_num_heads"] = attn_cfg["num_heads"]
        if "num_heads_kv" in attn_cfg:
            kw["attn_num_kv_heads"] = attn_cfg["num_heads_kv"]
        if "head_dim" in attn_cfg:
            kw["attn_head_dim"] = attn_cfg["head_dim"]
        kw["attn_rotary_dim"] = attn_cfg.get("rotary_emb_dim", 0)
    return ModelConfig(**kw)


def _np(t) -> np.ndarray:
    """torch tensor / array-like -> float32 numpy."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, dtype=np.float32)


def import_state_dict(state_dict: dict, cfg: ModelConfig) -> dict:
    """torch MambaLMHeadModel state dict -> layer-stacked JAX param tree.

    Hybrid (Jamba-style) checkpoints interleave MHA mixers at
    ``attn_layer_idx`` (mamba_ssm's ``MHA`` module: packed ``Wqkv`` +
    ``out_proj``); those layers land in the separately-stacked
    ``attn_blocks`` tree, matching ``init_lm_params``'s split.
    """
    sd = {k: _np(v) for k, v in state_dict.items()}
    n = cfg.n_layer
    attn_idx = set(cfg.attn_layer_idx or ())

    def attn_layer(i: int) -> dict:
        pre = f"backbone.layers.{i}."
        wqkv = sd[pre + "mixer.Wqkv.weight"]
        nh = cfg.effective_attn_num_heads
        nkv = cfg.effective_attn_num_kv_heads
        hd = cfg.effective_attn_head_dim
        want = (nh + 2 * nkv) * hd
        if wqkv.shape[0] != want:
            raise ValueError(
                f"layer {i}: Wqkv rows {wqkv.shape[0]} != "
                f"(nh={nh} + 2*nkv={nkv}) * head_dim={hd} = {want}; "
                "check attn_cfg (num_heads/num_heads_kv/head_dim)"
            )
        mixer = {"wqkv": {"kernel": wqkv.T},
                 "out_proj": {"kernel": sd[pre + "mixer.out_proj.weight"].T}}
        for name, ours in [("Wqkv", "wqkv"), ("out_proj", "out_proj")]:
            if pre + f"mixer.{name}.bias" in sd:
                mixer[ours]["bias"] = sd[pre + f"mixer.{name}.bias"]
        block = {"norm": {"weight": sd[pre + "norm.weight"]}, "mixer": mixer}
        if cfg.d_intermediate > 0:
            block["norm2"] = {"weight": sd[pre + "norm2.weight"]}
            block["mlp"] = {
                "fc1": {"kernel": sd[pre + "mlp.fc1.weight"].T},
                "fc2": {"kernel": sd[pre + "mlp.fc2.weight"].T},
            }
        return block

    def layer(i: int) -> dict:
        pre = f"backbone.layers.{i}."
        mixer = {}
        mixer["in_proj"] = {"kernel": sd[pre + "mixer.in_proj.weight"].T}
        if pre + "mixer.in_proj.bias" in sd:
            mixer["in_proj"]["bias"] = sd[pre + "mixer.in_proj.bias"]
        conv_w = sd[pre + "mixer.conv1d.weight"]  # (ch, 1, width)
        mixer["conv"] = {"kernel": conv_w.reshape(conv_w.shape[0], conv_w.shape[-1])}
        if pre + "mixer.conv1d.bias" in sd:
            mixer["conv"]["bias"] = sd[pre + "mixer.conv1d.bias"]
        mixer["A_log"] = sd[pre + "mixer.A_log"]
        mixer["D"] = sd[pre + "mixer.D"]
        mixer["out_proj"] = {"kernel": sd[pre + "mixer.out_proj.weight"].T}
        if pre + "mixer.out_proj.bias" in sd:
            mixer["out_proj"]["bias"] = sd[pre + "mixer.out_proj.bias"]
        if cfg.ssm_layer == "mamba2":
            mixer["dt_bias"] = sd[pre + "mixer.dt_bias"]
            mixer["norm"] = {"weight": sd[pre + "mixer.norm.weight"]}
        else:
            mixer["x_proj"] = {"kernel": sd[pre + "mixer.x_proj.weight"].T}
            mixer["dt_proj"] = {
                "kernel": sd[pre + "mixer.dt_proj.weight"].T,
                "bias": sd[pre + "mixer.dt_proj.bias"],
            }
        block = {"norm": {"weight": sd[pre + "norm.weight"]}, "mixer": mixer}
        if cfg.d_intermediate > 0:
            block["norm2"] = {"weight": sd[pre + "norm2.weight"]}
            block["mlp"] = {
                "fc1": {"kernel": sd[pre + "mlp.fc1.weight"].T},
                "fc2": {"kernel": sd[pre + "mlp.fc2.weight"].T},
            }
        return block

    import jax

    def stack(trees):
        return jax.tree.map(lambda *xs: jnp.asarray(np.stack(xs)), *trees)

    blocks = stack([layer(i) for i in range(n) if i not in attn_idx])
    attn_blocks = (
        stack([attn_layer(i) for i in range(n) if i in attn_idx])
        if attn_idx
        else None
    )

    emb = sd["backbone.embedding.weight"]
    vp = cfg.vocab_size_padded
    if emb.shape[0] < vp:  # pad rows like pad_vocab_size_multiple does
        emb = np.concatenate(
            [emb, np.zeros((vp - emb.shape[0], emb.shape[1]), emb.dtype)]
        )
    params = {
        "embedding": jnp.asarray(emb),
        "blocks": blocks,
        "norm_f": {"weight": jnp.asarray(sd["backbone.norm_f.weight"])},
    }
    if attn_blocks is not None:
        params["attn_blocks"] = attn_blocks
    if not cfg.tie_embeddings and "lm_head.weight" in sd:
        params["lm_head"] = {"kernel": jnp.asarray(sd["lm_head.weight"].T)}
    return params


def load_hf_checkpoint(path: str, cfg: ModelConfig | None = None):
    """Load (params, cfg) from a local HF-style directory or .pt file.

    Directory: expects ``config.json`` + ``pytorch_model.bin``.
    File: a torch checkpoint holding either a raw state dict or the
    reference trainer's ``{"model": state_dict, ...}`` wrapper
    (/root/reference/train.py:154-158).
    """
    import torch

    if os.path.isdir(path):
        with open(os.path.join(path, "config.json")) as f:
            cfg = config_from_hf_json(json.load(f))
        sd = torch.load(
            os.path.join(path, "pytorch_model.bin"),
            map_location="cpu", weights_only=True,
        )
    else:
        obj = torch.load(path, map_location="cpu", weights_only=True)
        sd = obj.get("model", obj) if isinstance(obj, dict) else obj
        assert cfg is not None, "pass a ModelConfig when loading a bare .pt"
    sd = {k.removeprefix("module."): v for k, v in sd.items()}  # DDP prefix
    return import_state_dict(sd, cfg), cfg
