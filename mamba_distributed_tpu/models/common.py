"""Shared init + linear helpers for the functional model stack.

Initialization matches the *distributions* used by the reference's model
builder (``mamba_ssm.models.mixer_seq_simple._init_weights`` and the mixer
constructors in mamba-ssm 2.2.2, the package pinned at reference
requirements.txt:2):

  * Linear weights: kaiming-uniform(a=sqrt(5)) == U(-1/sqrt(fan_in), +1/sqrt(fan_in))
  * Linear biases: zeros (except dt/conv, which have special inits)
  * Embedding: N(0, initializer_range=0.02)
  * Residual out-projections: same uniform, then / sqrt(n_residuals * n_layer)
    when ``rescale_prenorm_residual`` (GPT-2-style depth rescale)
  * Depthwise conv: PyTorch Conv1d default == U(+-1/sqrt(width)) for both
    weight and bias (fan_in = in_channels/groups * width = width)

Weights are stored as (in_features, out_features) so the forward pass is a
plain ``x @ W`` (row-major friendly for the MXU); this is the transpose of
the torch convention, handled by the HF importer.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def uniform_fan_in(key: jax.Array, shape: tuple[int, ...], fan_in: int,
                   dtype=jnp.float32) -> jax.Array:
    """PyTorch Linear/Conv default init: U(-1/sqrt(fan_in), +1/sqrt(fan_in))."""
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


def init_linear(key: jax.Array, d_in: int, d_out: int, bias: bool = False,
                dtype=jnp.float32) -> dict:
    p = {"kernel": uniform_fan_in(key, (d_in, d_out), d_in, dtype)}
    if bias:
        p["bias"] = jnp.zeros((d_out,), dtype)
    return p


def linear(params: dict, x: jax.Array, compute_dtype=jnp.bfloat16) -> jax.Array:
    """bf16 matmul with fp32 accumulation (MXU-native), bf16 output.

    Int8-quantized kernels (ops/quant.py: ``{"kernel": int8, "scale":
    f32}``, scale keeping the kernel's rank with the reduced axis sized
    1) dequantize AT USE — the scale folds into the output for
    column-scaled weights (``(x @ q) * scale``) or into the activation
    for row-scaled ones (``(x * scale) @ q``), so no full-precision
    weight copy is ever materialized (int8 values are exact in bf16:
    the cast feeding the dot is lossless).

    Multi-tenant LoRA serving (serving/adapters.py) attaches
    ``params["lora"] = {"A": (N, d_in, r), "B": (N, r, d_out),
    "ids": (b,)}`` — stacked per-adapter factor pools plus the
    launch's per-ROW adapter ids — and the segmented batched-LoRA
    delta lands on the fp32 accumulator:

        y += (x @ A[ids]) @ B[ids]

    Row 0 of the pools is the all-zero "no adapter" entry, so id-0
    rows add an exact +0.0 and batches mixing adapters (or none)
    share this ONE launch.  The ``alpha/rank`` scale is folded into
    the stored B (serving/adapters.py), so no extra multiply rides
    the hot path.  Trees without a bound ``lora`` entry — training,
    plain serving, ``generate()`` — take the exact pre-LoRA path.
    """
    w = params["kernel"]
    scale = params.get("scale")
    x0 = x  # pre-scale activations (the LoRA delta reads the originals)
    if scale is not None and scale.shape[-1] == 1:
        # per-input-row scales (row-parallel weights): fold into x —
        # exact (diag(scale) commutes through the contraction)
        x = (x.astype(jnp.float32) * scale[..., 0].astype(jnp.float32))
        scale = None
    y = jnp.dot(
        x.astype(compute_dtype),
        w.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    if scale is not None:
        # per-output-column scales: fold into the fp32 accumulator
        y = y * scale.astype(jnp.float32)
    lora = params.get("lora")
    if lora is not None and "ids" in lora:
        a_sel = jnp.take(lora["A"], lora["ids"], axis=0)
        b_sel = jnp.take(lora["B"], lora["ids"], axis=0)
        xa = jnp.einsum(
            "b...d,bdr->b...r",
            x0.astype(compute_dtype), a_sel.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        y = y + jnp.einsum(
            "b...r,bro->b...o",
            xa.astype(compute_dtype), b_sel.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
    if "bias" in params:
        y = y + params["bias"].astype(jnp.float32)
    return y.astype(compute_dtype)


def init_dt_bias(key: jax.Array, shape: tuple[int, ...], dt_min: float,
                 dt_max: float, dt_init_floor: float) -> jax.Array:
    """Inverse-softplus(dt) with dt ~ LogUniform(dt_min, dt_max), floored.

    Same construction as the dt_bias init in both mamba-ssm mixers
    (modules/mamba_simple.py and modules/mamba2.py): softplus(dt_bias)
    lands the initial timestep in [dt_min, dt_max] on a log scale.
    """
    u = jax.random.uniform(key, shape, jnp.float32)
    dt = jnp.exp(u * (math.log(dt_max) - math.log(dt_min)) + math.log(dt_min))
    dt = jnp.maximum(dt, dt_init_floor)
    # inverse softplus: x = dt + log(1 - exp(-dt))
    return dt + jnp.log(-jnp.expm1(-dt))


def init_conv(key: jax.Array, channels: int, width: int, bias: bool) -> dict:
    kw, kb = jax.random.split(key)
    p = {"kernel": uniform_fan_in(kw, (channels, width), width)}
    if bias:
        p["bias"] = uniform_fan_in(kb, (channels,), width)
    return p


def check_no_decode_state_under_sp(
    seq_ctx, initial_conv_state, initial_ssm_state, return_final_state: bool
) -> None:
    """Sequence parallelism is a training/eval path; decode-state carry
    through a mixer is a single-device concern.  Raise loudly rather than
    silently ignoring the carry (shared by the mamba1/mamba2 mixers)."""
    if seq_ctx is not None and (
        initial_conv_state is not None
        or initial_ssm_state is not None
        or return_final_state
    ):
        raise ValueError(
            "sequence parallelism is a training/eval path: decode-state "
            "carry (initial states / return_final_state) is not supported "
            "under seq_ctx"
        )
