"""Mamba-1 mixer (selective scan), TPU-native.

Functional equivalent of ``mamba_ssm.modules.mamba_simple.Mamba`` (mamba-ssm
2.2.2) — the mixer the reference's default ``ssm_cfg={}`` actually builds
(SURVEY.md §2.4 discrepancy).  Compute rides the in-tree chunked selective
scan (`ops/scan.py`) instead of the CUDA kernel.

Forward:  u -> in_proj -> split(x, z) -> causal_conv1d(x) ->
          x_proj -> (dt, B, C) -> dt_proj -> selective_scan(..., z=z) ->
          out_proj
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from mamba_distributed_tpu.config import ModelConfig
from mamba_distributed_tpu.models.common import (
    check_no_decode_state_under_sp,
    init_conv,
    init_dt_bias,
    init_linear,
    linear,
)
from mamba_distributed_tpu.ops.conv import causal_conv1d, causal_conv1d_update
from mamba_distributed_tpu.ops.scan import selective_scan, selective_state_update


def init_mamba1_params(key: jax.Array, cfg: ModelConfig) -> dict:
    di = cfg.d_inner
    ds = cfg.effective_d_state
    dtr = cfg.effective_dt_rank
    k_in, k_conv, k_x, k_dtw, k_dtb, k_out = jax.random.split(key, 6)

    # dt_proj weight: U(+-dt_rank^-0.5 * dt_scale) for "random",
    # constant for "constant" (mamba_simple.py dt_init branch)
    dt_init_std = dtr**-0.5 * cfg.dt_scale
    if cfg.dt_init == "random":
        dt_w = jax.random.uniform(
            k_dtw, (dtr, di), jnp.float32, -dt_init_std, dt_init_std
        )
    elif cfg.dt_init == "constant":
        dt_w = jnp.full((dtr, di), dt_init_std, jnp.float32)
    else:
        raise ValueError(cfg.dt_init)

    # S4D-real init: A[d, n] = n+1  ->  A_log = log(A)
    A = jnp.tile(jnp.arange(1, ds + 1, dtype=jnp.float32)[None, :], (di, 1))

    params = {
        "in_proj": init_linear(k_in, cfg.d_model, 2 * di, cfg.proj_bias),
        "conv": init_conv(k_conv, di, cfg.d_conv, cfg.conv_bias),
        "x_proj": init_linear(k_x, di, dtr + 2 * ds, False),
        "dt_proj": {
            "kernel": dt_w,
            "bias": init_dt_bias(
                k_dtb, (di,), cfg.dt_min, cfg.dt_max, cfg.dt_init_floor
            ),
        },
        "A_log": jnp.log(A),
        "D": jnp.ones((di,), jnp.float32),
        "out_proj": init_linear(k_out, di, cfg.d_model, cfg.proj_bias),
    }
    if cfg.rescale_prenorm_residual:
        n_residuals = 2 if cfg.d_intermediate > 0 else 1
        params["out_proj"]["kernel"] = params["out_proj"]["kernel"] / math.sqrt(
            n_residuals * cfg.n_layer
        )
    return params


def mamba1_mixer(
    params: dict,
    cfg: ModelConfig,
    u: jax.Array,
    initial_conv_state: jax.Array | None = None,
    initial_ssm_state: jax.Array | None = None,
    return_final_state: bool = False,
    seq_ctx=None,
    token_mask: jax.Array | None = None,
):
    """Full-sequence Mamba-1 mixer forward.

    u (b, t, d_model) -> y (b, t, d_model) [, (conv_state, ssm_state)].

    ``token_mask`` (b, t) {0,1} zeroes the conv/scan inputs at masked
    positions (left-padded bucketed prefill, inference/bucketing.py):
    with x=0 the selective scan's update term dt*B*x vanishes and the
    state only decays, so a zero initial state stays zero through the
    pad prefix — same contract as mamba2_mixer.
    """
    di = cfg.d_inner
    ds = cfg.effective_d_state
    dtr = cfg.effective_dt_rank
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    check_no_decode_state_under_sp(
        seq_ctx, initial_conv_state, initial_ssm_state, return_final_state
    )

    xz = linear(params["in_proj"], u, compute_dtype)
    x, z = xz[..., :di], xz[..., di:]

    if token_mask is not None:
        if seq_ctx is not None:
            raise ValueError("token_mask is a single-device prefill feature")
        x = x * token_mask[..., None].astype(x.dtype)
    if seq_ctx is not None:
        from mamba_distributed_tpu.parallel.seq_parallel import sp_conv1d

        x, conv_state = sp_conv1d(
            seq_ctx, x, params["conv"]["kernel"],
            params["conv"].get("bias"), "silu",
        )
    else:
        x, conv_state = causal_conv1d(
            x, params["conv"]["kernel"], params["conv"].get("bias"),
            activation="silu",
            initial_state=initial_conv_state,
            return_final_state=True,
            impl=cfg.conv_impl,
        )
    if token_mask is not None:
        x = x * token_mask[..., None].astype(x.dtype)

    x_db = linear(params["x_proj"], x, compute_dtype)
    dt = x_db[..., :dtr]
    B = x_db[..., dtr : dtr + ds].astype(jnp.float32)
    C = x_db[..., dtr + ds :].astype(jnp.float32)
    # dt_proj without bias; the bias folds into the scan's delta_bias so the
    # softplus happens in fp32 inside the kernel (selective_scan_interface
    # does the same).
    dt = jnp.dot(
        dt.astype(compute_dtype),
        params["dt_proj"]["kernel"].astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )

    A = -jnp.exp(params["A_log"])  # (di, ds)
    scan_kw = dict(
        D=params["D"], z=z, delta_bias=params["dt_proj"]["bias"],
        delta_softplus=True,
    )
    if seq_ctx is not None:
        from mamba_distributed_tpu.parallel.seq_parallel import sp_selective_scan

        y, ssm_state = sp_selective_scan(
            seq_ctx, x, dt, A, B, C, ssm_impl=cfg.ssm_impl, **scan_kw
        )
    else:
        if cfg.ssm_impl == "pallas":
            from mamba_distributed_tpu.ops.pallas import selective_scan_pallas

            scan_fn = selective_scan_pallas
        else:
            scan_fn = selective_scan
        if initial_ssm_state is None and not return_final_state:
            # training path: keeps the Pallas backend on its custom-vjp route
            y = scan_fn(x, dt, A, B, C, **scan_kw)
            ssm_state = None
        else:
            y, ssm_state = scan_fn(
                x, dt, A, B, C, **scan_kw,
                initial_state=initial_ssm_state, return_final_state=True,
            )
    # remat_policy="mixer" save point (models/lm.py:_remat)
    y = checkpoint_name(y, "mixer_out")
    out = linear(params["out_proj"], y, compute_dtype)
    if return_final_state:
        return out, (conv_state, ssm_state)
    return out


def init_mamba1_state(cfg: ModelConfig, batch: int, dtype=None):
    """conv cache in the compute dtype (matches full-sequence prefill);
    SSM state fp32 (matches the scan's carry)."""
    di = cfg.d_inner
    ds = cfg.effective_d_state
    if dtype is None:
        dtype = jnp.dtype(cfg.compute_dtype)
    conv_state = jnp.zeros((batch, cfg.d_conv - 1, di), dtype)
    ssm_state = jnp.zeros((batch, di, ds), jnp.float32)
    return conv_state, ssm_state


def mamba1_mixer_step(
    params: dict,
    cfg: ModelConfig,
    u_t: jax.Array,
    conv_state: jax.Array,
    ssm_state: jax.Array,
):
    """O(1) single-token decode step for Mamba-1."""
    di = cfg.d_inner
    ds = cfg.effective_d_state
    dtr = cfg.effective_dt_rank
    compute_dtype = jnp.dtype(cfg.compute_dtype)

    xz = linear(params["in_proj"], u_t, compute_dtype)
    x, z = xz[..., :di], xz[..., di:]

    x, conv_state = causal_conv1d_update(
        x, conv_state, params["conv"]["kernel"], params["conv"].get("bias"),
        activation="silu",
    )
    x_db = linear(params["x_proj"], x, compute_dtype)
    dt = x_db[..., :dtr]
    B = x_db[..., dtr : dtr + ds].astype(jnp.float32)
    C = x_db[..., dtr + ds :].astype(jnp.float32)
    dt = jnp.dot(
        dt.astype(compute_dtype),
        params["dt_proj"]["kernel"].astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    A = -jnp.exp(params["A_log"])
    y, ssm_state = selective_state_update(
        ssm_state, x, dt, A, B, C,
        D=params["D"], z_t=z,
        dt_bias=params["dt_proj"]["bias"], dt_softplus=True,
    )
    out = linear(params["out_proj"], y, compute_dtype)
    return out, (conv_state, ssm_state)
