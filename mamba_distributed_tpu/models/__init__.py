"""Model stack: Mamba-1 / Mamba-2 mixers, attention, full LM.

TPU-native functional models: parameters are plain pytrees (nested dicts of
jnp arrays), built by explicit ``init_*`` functions and consumed by pure
``apply`` functions — no module framework in the hot path, which keeps
scan-over-layers, remat, and pjit sharding annotations fully explicit.
"""

from mamba_distributed_tpu.models.lm import (
    init_lm_params,
    lm_forward,
    lm_loss,
    count_params,
)
from mamba_distributed_tpu.models.mamba1 import init_mamba1_params, mamba1_mixer
from mamba_distributed_tpu.models.mamba2 import init_mamba2_params, mamba2_mixer

__all__ = [
    "init_lm_params",
    "lm_forward",
    "lm_loss",
    "count_params",
    "init_mamba1_params",
    "mamba1_mixer",
    "init_mamba2_params",
    "mamba2_mixer",
]
