"""Mamba-2 mixer (SSD), TPU-native.

Functional equivalent of ``mamba_ssm.modules.mamba2.Mamba2`` (mamba-ssm
2.2.2, pinned at reference requirements.txt:2), the headline mixer of
BASELINE.json.  Projection layout, dt/A/D parameterization, and the gated
RMSNorm placement follow that module's semantics; the compute path is the
in-tree TPU SSD (`ops/ssd.py`) instead of Triton kernels.

Forward:  u -> in_proj -> split(z, xBC, dt) -> causal_conv1d(xBC) ->
          split(x, B, C) -> SSD(x, dt, A, B, C, D) -> gated RMSNorm(y, z)
          -> out_proj
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from mamba_distributed_tpu.config import ModelConfig
from mamba_distributed_tpu.models.common import (
    check_no_decode_state_under_sp,
    init_conv,
    init_dt_bias,
    init_linear,
    linear,
)
from mamba_distributed_tpu.ops.conv import causal_conv1d, causal_conv1d_update
from mamba_distributed_tpu.ops.norm import rms_norm_gated
from mamba_distributed_tpu.ops.ssd import ssd_chunked, ssd_state_update


def _dims(cfg: ModelConfig):
    di = cfg.d_inner
    ds = cfg.effective_d_state
    g = cfg.ngroups
    nh = cfg.nheads
    d_in_proj = 2 * di + 2 * g * ds + nh
    conv_dim = di + 2 * g * ds
    return di, ds, g, nh, d_in_proj, conv_dim


def init_mamba2_params(key: jax.Array, cfg: ModelConfig) -> dict:
    di, ds, g, nh, d_in_proj, conv_dim = _dims(cfg)
    k_in, k_conv, k_dt, k_a, k_out = jax.random.split(key, 5)
    params = {
        "in_proj": init_linear(k_in, cfg.d_model, d_in_proj, cfg.proj_bias),
        "conv": init_conv(k_conv, conv_dim, cfg.d_conv, cfg.conv_bias),
        "dt_bias": init_dt_bias(
            k_dt, (nh,), cfg.dt_min, cfg.dt_max, cfg.dt_init_floor
        ),
        # A ~ U(a_init_min, a_init_max), stored as log (A = -exp(A_log))
        "A_log": jnp.log(
            jax.random.uniform(
                k_a, (nh,), jnp.float32, cfg.a_init_min, cfg.a_init_max
            )
        ),
        "D": jnp.ones((di if cfg.d_has_hdim else nh,), jnp.float32),
        "norm": {"weight": jnp.ones((di,), jnp.float32)},
        "out_proj": init_linear(k_out, di, cfg.d_model, cfg.proj_bias),
    }
    if cfg.rescale_prenorm_residual:
        n_residuals = 2 if cfg.d_intermediate > 0 else 1
        params["out_proj"]["kernel"] = params["out_proj"]["kernel"] / math.sqrt(
            n_residuals * cfg.n_layer
        )
    return params


def _split_zxbcdt(zxbcdt: jax.Array, cfg: ModelConfig):
    di, ds, g, nh, _, conv_dim = _dims(cfg)
    z = zxbcdt[..., :di]
    xBC = zxbcdt[..., di : di + conv_dim]
    dt = zxbcdt[..., di + conv_dim :]
    return z, xBC, dt


def _split_xbc(xBC: jax.Array, cfg: ModelConfig):
    di, ds, g, _, _, _ = _dims(cfg)
    x = xBC[..., :di]
    B = xBC[..., di : di + g * ds]
    C = xBC[..., di + g * ds :]
    return x, B, C


def mamba2_mixer(
    params: dict,
    cfg: ModelConfig,
    u: jax.Array,
    initial_conv_state: jax.Array | None = None,
    initial_ssm_state: jax.Array | None = None,
    return_final_state: bool = False,
    seq_ctx=None,
    token_mask: jax.Array | None = None,
):
    """Full-sequence Mamba-2 mixer forward.

    Args:
      u: (b, t, d_model) in compute dtype.
      initial_conv_state: (b, d_conv-1, conv_dim) decode/prefill carry
        (single-device only — mutually exclusive with ``seq_ctx``).
      initial_ssm_state: (b, nheads, headdim, d_state) carry (same).
      seq_ctx: optional ``parallel.seq_parallel.SeqContext`` — when given,
        the conv halo and SSD chunk-state passing run across the mesh's
        ``seq`` axis instead of locally; decode-state carry is rejected.
      token_mask: optional (b, t) {0,1} — zeroes the conv/SSM inputs at
        masked positions so a left-padded prompt produces the same scan
        state as the unpadded one (inference/bucketing.py).  Masked
        BEFORE the conv (pad inputs must look like the zero initial conv
        state) and AFTER it (the conv bias + silu would otherwise leak a
        nonzero x/B into the SSM update at pad positions).

    Returns: y (b, t, d_model) [, (conv_state, ssm_state)].
    """
    di, ds, g, nh, _, conv_dim = _dims(cfg)
    b, t, _ = u.shape
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    check_no_decode_state_under_sp(
        seq_ctx, initial_conv_state, initial_ssm_state, return_final_state
    )

    zxbcdt = linear(params["in_proj"], u, compute_dtype)
    z, xBC, dt = _split_zxbcdt(zxbcdt, cfg)

    if token_mask is not None:
        if seq_ctx is not None:
            raise ValueError("token_mask is a single-device prefill feature")
        xBC = xBC * token_mask[..., None].astype(xBC.dtype)
    if seq_ctx is not None:
        from mamba_distributed_tpu.parallel.seq_parallel import sp_conv1d

        xBC, conv_state = sp_conv1d(
            seq_ctx, xBC, params["conv"]["kernel"],
            params["conv"].get("bias"), "silu",
        )
    else:
        xBC, conv_state = causal_conv1d(
            xBC,
            params["conv"]["kernel"],
            params["conv"].get("bias"),
            activation="silu",
            initial_state=initial_conv_state,
            return_final_state=True,
            impl=cfg.conv_impl,
        )
    if token_mask is not None:
        xBC = xBC * token_mask[..., None].astype(xBC.dtype)
    x, B, C = _split_xbc(xBC, cfg)

    x = x.reshape(b, t, nh, cfg.headdim)
    B = B.reshape(b, t, g, ds)
    C = C.reshape(b, t, g, ds)
    dtf = jax.nn.softplus(
        dt.astype(jnp.float32) + params["dt_bias"][None, None, :]
    )
    A = -jnp.exp(params["A_log"])  # (nh,)
    D = params["D"].reshape(nh, cfg.headdim) if cfg.d_has_hdim else params["D"]

    if seq_ctx is not None:
        from mamba_distributed_tpu.parallel.seq_parallel import sp_ssd

        y, ssm_state = sp_ssd(
            seq_ctx, x, dtf, A, B, C, cfg.chunk_size, D,
            compute_dtype=compute_dtype, ssm_impl=cfg.ssm_impl,
        )
    elif cfg.ssm_impl == "pallas":
        from mamba_distributed_tpu.ops.pallas import ssd_chunked_pallas

        if initial_ssm_state is None and not return_final_state:
            y = ssd_chunked_pallas(
                x, dtf, A, B, C, chunk_size=cfg.chunk_size, D=D,
                compute_dtype=compute_dtype,
            )
            ssm_state = None
        else:
            y, ssm_state = ssd_chunked_pallas(
                x, dtf, A, B, C, chunk_size=cfg.chunk_size, D=D,
                initial_state=initial_ssm_state, return_final_state=True,
                compute_dtype=compute_dtype,
            )
    else:
        y, ssm_state = ssd_chunked(
            x, dtf, A, B, C,
            chunk_size=cfg.chunk_size,
            D=D,
            initial_state=initial_ssm_state,
            return_final_state=True,
            compute_dtype=compute_dtype,
        )
    # remat_policy="mixer": the scan output is the save point — the
    # backward then never recomputes the SSD scan, the priciest part of
    # the block (models/lm.py:_remat)
    y = checkpoint_name(y, "mixer_out")
    y = y.reshape(b, t, di)
    y = rms_norm_gated(
        y, z, params["norm"]["weight"], cfg.norm_eps,
        group_size=di // g if g > 1 else None,
    )
    out = linear(params["out_proj"], y, compute_dtype)
    if return_final_state:
        return out, (conv_state, ssm_state)
    return out


def init_mamba2_state(cfg: ModelConfig, batch: int, dtype=None):
    """Zero decode state: (conv_state, ssm_state) for one mixer.

    conv cache in the compute dtype (matching what the full-sequence
    prefill produces), SSM state in fp32 (matching state_passing).
    """
    di, ds, g, nh, _, conv_dim = _dims(cfg)
    if dtype is None:
        dtype = jnp.dtype(cfg.compute_dtype)
    conv_state = jnp.zeros((batch, cfg.d_conv - 1, conv_dim), dtype)
    ssm_state = jnp.zeros((batch, nh, cfg.headdim, ds), jnp.float32)
    return conv_state, ssm_state


def mamba2_mixer_step(
    params: dict,
    cfg: ModelConfig,
    u_t: jax.Array,
    conv_state: jax.Array,
    ssm_state: jax.Array,
):
    """O(1) single-token decode step.

    u_t (b, d_model) -> (y_t (b, d_model), (conv_state, ssm_state)).
    Numerically matches the full-sequence path token-for-token (the decode
    parity test pins this).
    """
    di, ds, g, nh, _, conv_dim = _dims(cfg)
    b, _ = u_t.shape
    compute_dtype = jnp.dtype(cfg.compute_dtype)

    zxbcdt = linear(params["in_proj"], u_t, compute_dtype)
    z, xBC, dt = _split_zxbcdt(zxbcdt, cfg)

    xBC, conv_state = causal_conv1d_update(
        xBC, conv_state, params["conv"]["kernel"], params["conv"].get("bias"),
        activation="silu",
    )
    x, B, C = _split_xbc(xBC, cfg)

    x = x.reshape(b, nh, cfg.headdim)
    B = B.reshape(b, g, ds)
    C = C.reshape(b, g, ds)
    A = -jnp.exp(params["A_log"])
    D = params["D"].reshape(nh, cfg.headdim) if cfg.d_has_hdim else params["D"]

    y, ssm_state = ssd_state_update(
        ssm_state, x, dt.astype(jnp.float32), A, B, C, D,
        dt_bias=params["dt_bias"], dt_softplus=True,
    )
    y = y.reshape(b, di)
    y = rms_norm_gated(
        y, z, params["norm"]["weight"], cfg.norm_eps,
        group_size=di // g if g > 1 else None,
    )
    out = linear(params["out_proj"], y, compute_dtype)
    return out, (conv_state, ssm_state)
