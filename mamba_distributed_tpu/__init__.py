"""mamba_distributed_tpu — a TPU-native (JAX/XLA/Pallas/pjit) framework with
the capabilities of pie33000/mamba-distributed.

Subpackages:
  config    — dataclass configs + the five BASELINE presets
  models    — Mamba-1 / Mamba-2 / hybrid flax models
  ops       — TPU-native kernels (conv1d, selective scan, SSD, norms)
  parallel  — mesh, sharding rules, sequence parallelism
  data      — token-shard pipeline
  training  — optimizer, train step, trainer loop, checkpointing
  eval      — HellaSwag harness
  inference — recurrent O(1) decode + sampling
"""

__version__ = "0.1.0"

from mamba_distributed_tpu.config import (
    DataConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
    get_preset,
    PRESETS,
)

__all__ = [
    "DataConfig",
    "MeshConfig",
    "ModelConfig",
    "TrainConfig",
    "get_preset",
    "PRESETS",
    "__version__",
]
