"""Pallas TPU kernels for the hot ops.

The XLA formulations in ``ops/ssd.py``/``ops/scan.py`` are correct but pay
in HBM traffic: the SSD path materializes the (l x l) intra-chunk decay
matrix (O(b*t*h*l) bytes) per layer, and the selective-scan path remats
around a transient (b, l, d, n) tensor.  These kernels keep those
intermediates in VMEM instead — the SSD decay matrix is rebuilt per tile,
the selective-scan state lives in registers for the whole sequence — which
is where the MFU headroom lives (SURVEY.md §7 stage 5).  Decode-side,
``ragged_paged_decode_attention`` walks the serving pool's paged KV per
slot (models/attention.py).

Every submodule takes ``CompilerParams`` from ``ops.pallas.common`` — a
compat alias over jax's TPUCompilerParams/CompilerParams rename — so
importing ANY kernel module works on either jax API, in any import order
(a partially imported package can no longer shadow the rest).
"""

from mamba_distributed_tpu.ops.pallas.attention_kernels import (
    flash_sdpa_causal,
    ragged_paged_decode_attention,
)
from mamba_distributed_tpu.ops.pallas.scan_kernels import selective_scan_pallas
from mamba_distributed_tpu.ops.pallas.ssd_kernels import ssd_chunked_pallas

__all__ = [
    "flash_sdpa_causal",
    "ragged_paged_decode_attention",
    "selective_scan_pallas",
    "ssd_chunked_pallas",
]
