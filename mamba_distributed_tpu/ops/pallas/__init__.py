"""Pallas TPU kernels for the hot ops.

The XLA formulations in ``ops/ssd.py`` are correct and MXU-friendly but
materialize the (l x l) intra-chunk decay matrix (O(b*t*h*l) bytes) in HBM
each layer; these kernels rebuild it in VMEM per tile instead, which is
where the MFU headroom lives (SURVEY.md §7 stage 5).
"""

from mamba_distributed_tpu.ops.pallas.ssd_kernels import ssd_chunked_pallas

__all__ = ["ssd_chunked_pallas"]
