"""Pallas selective-scan (Mamba-1) kernel.

TPU-native counterpart of the reference dependency's CUDA selective scan
(``mamba_ssm/csrc/selective_scan/`` in mamba-ssm 2.2.2) — re-derived for
the VPU/VMEM model rather than translated:

  * grid = (batch, d-blocks, t-tiles); the recurrent state lives in a VMEM
    scratch laid out ``(n, d_blk)`` (a (16, 128)-lane vreg tile is exactly
    one state update's working set) and is carried across the sequential
    t-tile dimension, so arbitrarily long sequences stream through a
    bounded VMEM budget;
  * the time loop is sequential *inside* the kernel (the recurrence is
    sequential; the CUDA kernel does the same) — HBM traffic is just
    u/delta in, y out: nothing of shape (b, t, d, n) ever exists, unlike
    the XLA associative-scan path whose per-chunk intermediates are remat
    tricks around exactly that tensor;
  * batch and d-block grid dimensions are marked parallel (megacore);
    state math is fp32 like the CUDA kernel.

Training uses ``jax.custom_vjp`` with a **Pallas backward** (counterpart
of the reference dep's fused CUDA backward in
``mamba_ssm/csrc/selective_scan/selective_scan_bwd_*.cu``): a first
kernel re-runs the forward storing only per-tile entry states, then a
reverse-time kernel walks the t-tiles backwards, rebuilds the in-tile
states in a VMEM scratch from the tile's entry state (the same
recompute-per-chunk trade the CUDA kernel makes with shared memory),
and accumulates du/ddt/dA/dB/dC as it sweeps.  Gradient parity vs the
XLA associative-scan path is pinned by tests/test_pallas.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mamba_distributed_tpu.ops.pallas.common import (
    CompilerParams,
    resolve_interpret,
)
from mamba_distributed_tpu.ops.scan import _prep


_OUTER = (((0,), (0,)), ((), ()))    # (1, n) x (1, d) -> (n, d)
_MATVEC = (((1,), (0,)), ((), ()))   # (1, n) x (n, d) -> (1, d)
_LANES = (((1,), (1,)), ((), ()))    # (1, d) x (n, d) -> (1, n)


def _m1_step(h, At, dt_t, u_t, B_row):
    """One recurrence step: h' = h * exp(A dt) + outer(B, dt u).

    ``B_row`` is (1, n); the outer product runs as a singleton-contracted
    dot_general — Mosaic supports no (1, n) -> (n, 1) shape cast, so
    row-vector B/C never get transposed in-kernel (hardware lesson, r4).
    """
    return h * jnp.exp(At * dt_t) + jax.lax.dot_general(
        B_row, dt_t * u_t, _OUTER, preferred_element_type=jnp.float32,
    )


def _m1_scan_kernel(
    u_ref, dt_ref, At_ref, B_ref, C_ref, h0_ref, y_ref, hT_ref, h_scratch,
    *, nt: int
):
    """Sequential selective scan for one (batch, d-block, t-tile) cell.

    u/dt (1, tb, dblk) fp32; At (n, dblk); B/C (1, tb, n); h0 (1, n, dblk).
    The state is carried across t-tiles in ``h_scratch`` (n, dblk); the
    final tile writes it to hT (1, n, dblk).
    """
    ti = pl.program_id(2)

    @pl.when(ti == 0)
    def _():
        h_scratch[...] = h0_ref[0]

    At = At_ref[...]          # (n, dblk)
    tb = u_ref.shape[1]

    def body(i, h):
        dt_t = dt_ref[0, pl.ds(i, 1)]              # (1, dblk)
        u_t = u_ref[0, pl.ds(i, 1)]                # (1, dblk)
        B_row = B_ref[0, pl.ds(i, 1)]              # (1, n)
        C_row = C_ref[0, pl.ds(i, 1)]              # (1, n)
        h = _m1_step(h, At, dt_t, u_t, B_row)
        y_ref[0, pl.ds(i, 1)] = jax.lax.dot_general(
            C_row, h, _MATVEC, preferred_element_type=jnp.float32,
        )
        return h

    h_scratch[...] = jax.lax.fori_loop(0, tb, body, h_scratch[...])

    @pl.when(ti == nt - 1)
    def _():
        hT_ref[0] = h_scratch[...]


def _divisor_up_to(x: int, target: int) -> int:
    """Largest divisor of x that is <= target."""
    blk = min(x, target)
    while x % blk != 0:
        blk -= 1
    return blk


def _pick_blocks(t: int, d: int) -> tuple[int, int]:
    """(t_blk, dblk) dividing (t, d), sized for a few-MB VMEM footprint.

    dblk targets 512 lanes (a multiple of the 128-lane vreg width when d
    allows); t_blk then caps the u/dt/y tiles at ~2 MB each in fp32.
    """
    for cand in (512, 256, 128):
        if d % cand == 0:
            dblk = cand
            break
    else:
        dblk = _divisor_up_to(d, 512)
    t_target = max(1, (2 << 20) // (4 * dblk))  # ~2 MB fp32 per (tb, dblk) tile
    t_blk = _divisor_up_to(t, min(t, t_target))
    return t_blk, dblk


def _m1_pallas_fwd(uf, df, Af, Bf, Cf, h0, interpret):
    """fp32 core: (b,t,d)x2, (d,n), (b,t,n)x2, (b,d,n) -> y, final_state."""
    b, t, d = uf.shape
    n = Af.shape[-1]
    t_blk, dblk = _pick_blocks(t, d)
    nt = t // t_blk
    grid = (b, d // dblk, nt)

    io_spec = pl.BlockSpec((1, t_blk, dblk), lambda bi, di, ti: (bi, ti, di))
    bc_spec = pl.BlockSpec((1, t_blk, n), lambda bi, di, ti: (bi, ti, 0))
    st_spec = pl.BlockSpec((1, n, dblk), lambda bi, di, ti: (bi, 0, di))

    y, hT = pl.pallas_call(
        functools.partial(_m1_scan_kernel, nt=nt),
        grid=grid,
        in_specs=[
            io_spec,
            io_spec,
            pl.BlockSpec((n, dblk), lambda bi, di, ti: (0, di)),
            bc_spec,
            bc_spec,
            st_spec,
        ],
        out_specs=[io_spec, st_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, d), jnp.float32),
            jax.ShapeDtypeStruct((b, n, d), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((n, dblk), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(uf, df, Af.T, Bf, Cf, jnp.swapaxes(h0, 1, 2))
    return y, jnp.swapaxes(hT, 1, 2)


# ---------------------------------------------------------------------------
# Backward pass.  Recurrence (per batch, channel, state lane n):
#     h_i = h_{i-1} * e_i + dt_i u_i B_i,   e_i = exp(A dt_i)
#     y_i = <h_i, C_i>
# Reverse sweep with gh = dL/dh_i accumulated right-to-left:
#     gh   += C_i (x) dy_i
#     dC_i  = sum_d h_i dy_i            dB_i = sum_d gh dt_i u_i
#     ddt_i = sum_n gh (h_{i-1} A e_i + u_i B_i)
#     du_i  = dt_i sum_n gh B_i         dA  += gh e_i h_{i-1} dt_i
#     gh   *= e_i
# h_{i-1} is rebuilt per tile from a stored tile-entry state, so the
# backward's HBM footprint stays O(t/t_blk) states, not O(t).
# ---------------------------------------------------------------------------


def _m1_entry_states_kernel(
    u_ref, dt_ref, At_ref, B_ref, h0_ref, st_ref, h_scratch
):
    """Forward recompute writing each t-tile's *entry* state."""
    ti = pl.program_id(2)

    @pl.when(ti == 0)
    def _():
        h_scratch[...] = h0_ref[0]

    st_ref[0, 0] = h_scratch[...]
    At = At_ref[...]
    tb = u_ref.shape[1]

    def body(i, h):
        dt_t = dt_ref[0, pl.ds(i, 1)]
        u_t = u_ref[0, pl.ds(i, 1)]
        B_row = B_ref[0, pl.ds(i, 1)]              # (1, n)
        return _m1_step(h, At, dt_t, u_t, B_row)

    h_scratch[...] = jax.lax.fori_loop(0, tb, body, h_scratch[...])


def _m1_bwd_kernel(
    u_ref, dt_ref, At_ref, B_ref, C_ref, hin_ref, dy_ref, dfinal_ref,
    du_ref, ddt_ref, dA_ref, dB_ref, dC_ref, dh0_ref,
    gh_scratch, hbuf, dA_scratch, *, nt: int,
):
    """Reverse sweep over one (batch, d-block, reversed t-tile) cell.

    hbuf[i] holds h_{i-1} (the state *entering* step i), rebuilt from the
    tile's entry state; gh and the dA accumulator persist across the
    sequential (reversed) tile dimension in scratch.  ``dfinal`` (the
    final-state cotangent — zeros for an unseeded call) seeds gh at the
    reverse start; after the full sweep gh IS the initial-state gradient,
    emitted as ``dh0``.
    """
    ti = pl.program_id(2)

    @pl.when(ti == 0)
    def _():
        gh_scratch[...] = dfinal_ref[0]
        dA_scratch[...] = jnp.zeros_like(dA_scratch)

    At = At_ref[...]          # (n, dblk)
    tb = u_ref.shape[1]

    # forward in-tile recompute: hbuf[i] = state before step i
    def fwd_body(i, h):
        hbuf[pl.ds(i, 1)] = h[None]
        dt_t = dt_ref[0, pl.ds(i, 1)]
        u_t = u_ref[0, pl.ds(i, 1)]
        B_row = B_ref[0, pl.ds(i, 1)]
        return _m1_step(h, At, dt_t, u_t, B_row)

    jax.lax.fori_loop(0, tb, fwd_body, hin_ref[0, 0])

    ones_n = jnp.ones((1, At.shape[0]), jnp.float32)

    # reverse sweep (row-vector forms throughout: outer products and
    # sublane contractions via dot_general, never a (1, n) -> (n, 1) cast)
    def rev_body(k, carry):
        gh, dA = carry
        i = tb - 1 - k
        dt_t = dt_ref[0, pl.ds(i, 1)]              # (1, dblk)
        u_t = u_ref[0, pl.ds(i, 1)]
        dy_t = dy_ref[0, pl.ds(i, 1)]
        B_row = B_ref[0, pl.ds(i, 1)]              # (1, n)
        C_row = C_ref[0, pl.ds(i, 1)]
        hprev = hbuf[i]                            # (n, dblk)

        e_t = jnp.exp(At * dt_t)
        gh = gh + jax.lax.dot_general(             # += outer(C, dy)
            C_row, dy_t, _OUTER, preferred_element_type=jnp.float32,
        )
        hcur = _m1_step(hprev, At, dt_t, u_t, B_row)
        dC_ref[0, 0, pl.ds(i, 1)] = jax.lax.dot_general(
            dy_t, hcur, _LANES, preferred_element_type=jnp.float32,
        )                                          # (1, n)
        dB_ref[0, 0, pl.ds(i, 1)] = jax.lax.dot_general(
            dt_t * u_t, gh, _LANES, preferred_element_type=jnp.float32,
        )
        term = hprev * At * e_t + jax.lax.dot_general(
            B_row, u_t, _OUTER, preferred_element_type=jnp.float32,
        )
        ddt_ref[0, pl.ds(i, 1)] = jax.lax.dot_general(
            ones_n, gh * term, _MATVEC, preferred_element_type=jnp.float32,
        )                                          # (1, dblk) sublane sum
        du_ref[0, pl.ds(i, 1)] = dt_t * jax.lax.dot_general(
            B_row, gh, _MATVEC, preferred_element_type=jnp.float32,
        )
        ghe = gh * e_t
        dA = dA + ghe * hprev * dt_t
        return ghe, dA

    gh, dA = jax.lax.fori_loop(
        0, tb, rev_body, (gh_scratch[...], dA_scratch[...])
    )
    gh_scratch[...] = gh
    dA_scratch[...] = dA

    @pl.when(ti == nt - 1)
    def _():
        dA_ref[0] = dA_scratch[...]
        # gh after the earliest step == dL/d(initial state)
        dh0_ref[0] = gh_scratch[...]


def _m1_pallas_bwd_impl(uf, df, Af, Bf, Cf, dy, interpret,
                        h0=None, dfinal=None):
    """Entry-state recompute + reverse kernel + tiny XLA reductions.

    ``h0``/``dfinal`` are (b, d, n) seeded-call extras: the entry-state
    recompute starts from h0, dfinal seeds the reverse sweep, and the
    initial-state gradient comes back as the sixth output (b, d, n).
    """
    b, t, d = uf.shape
    n = Af.shape[-1]
    t_blk, dblk = _pick_blocks(t, d)
    # the reverse kernel keeps (t_blk, n, dblk) rebuilt states in VMEM;
    # shrink the tile if that buffer would exceed ~4 MB
    cap = max(1, (4 << 20) // (4 * n * dblk))
    if t_blk > cap:
        t_blk = _divisor_up_to(t, cap)
    nt = t // t_blk
    nd = d // dblk
    grid = (b, nd, nt)
    At = Af.T
    h0 = (
        jnp.zeros((b, n, d), jnp.float32)
        if h0 is None
        else jnp.swapaxes(h0, 1, 2).astype(jnp.float32)
    )
    dfinal = (
        jnp.zeros((b, n, d), jnp.float32)
        if dfinal is None
        else jnp.swapaxes(dfinal, 1, 2).astype(jnp.float32)
    )

    io_spec = pl.BlockSpec((1, t_blk, dblk), lambda bi, di, ti: (bi, ti, di))
    bc_spec = pl.BlockSpec((1, t_blk, n), lambda bi, di, ti: (bi, ti, 0))
    A_spec = pl.BlockSpec((n, dblk), lambda bi, di, ti: (0, di))
    seq_semantics = CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )

    entry_states = pl.pallas_call(
        _m1_entry_states_kernel,
        grid=grid,
        in_specs=[
            io_spec, io_spec, A_spec, bc_spec,
            pl.BlockSpec((1, n, dblk), lambda bi, di, ti: (bi, 0, di)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, n, dblk), lambda bi, di, ti: (bi, ti, 0, di)
        ),
        out_shape=jax.ShapeDtypeStruct((b, nt, n, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((n, dblk), jnp.float32)],
        compiler_params=seq_semantics,
        interpret=interpret,
    )(uf, df, At, Bf, h0)

    # reversed sequential tile order via the index maps
    rio_spec = pl.BlockSpec(
        (1, t_blk, dblk), lambda bi, di, ti: (bi, nt - 1 - ti, di)
    )
    rbc_spec = pl.BlockSpec(
        (1, t_blk, n), lambda bi, di, ti: (bi, nt - 1 - ti, 0)
    )
    st_spec = pl.BlockSpec((1, n, dblk), lambda bi, di, ti: (bi, 0, di))
    du, ddt, dA_part, dB_part, dC_part, dh0 = pl.pallas_call(
        functools.partial(_m1_bwd_kernel, nt=nt),
        grid=grid,
        in_specs=[
            rio_spec, rio_spec, A_spec, rbc_spec, rbc_spec,
            pl.BlockSpec((1, 1, n, dblk), lambda bi, di, ti: (bi, nt - 1 - ti, 0, di)),
            rio_spec,
            st_spec,
        ],
        out_specs=[
            rio_spec,
            rio_spec,
            pl.BlockSpec((1, n, dblk), lambda bi, di, ti: (bi, 0, di)),
            pl.BlockSpec((1, 1, t_blk, n), lambda bi, di, ti: (bi, di, nt - 1 - ti, 0)),
            pl.BlockSpec((1, 1, t_blk, n), lambda bi, di, ti: (bi, di, nt - 1 - ti, 0)),
            st_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, d), jnp.float32),
            jax.ShapeDtypeStruct((b, t, d), jnp.float32),
            jax.ShapeDtypeStruct((b, n, d), jnp.float32),
            jax.ShapeDtypeStruct((b, nd, t, n), jnp.float32),
            jax.ShapeDtypeStruct((b, nd, t, n), jnp.float32),
            jax.ShapeDtypeStruct((b, n, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((n, dblk), jnp.float32),
            pltpu.VMEM((t_blk, n, dblk), jnp.float32),
            pltpu.VMEM((n, dblk), jnp.float32),
        ],
        compiler_params=seq_semantics,
        interpret=interpret,
    )(uf, df, At, Bf, Cf, entry_states, dy, dfinal)

    dAf = jnp.sum(dA_part, axis=0).T           # (d, n)
    dBf = jnp.sum(dB_part, axis=1)             # (b, t, n)
    dCf = jnp.sum(dC_part, axis=1)
    return du, ddt, dAf, dBf, dCf, jnp.swapaxes(dh0, 1, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def _m1_core(uf, df, Af, Bf, Cf, h0, interpret, return_final_state):
    y, hT = _m1_pallas_fwd(uf, df, Af, Bf, Cf, h0, interpret)
    return (y, hT) if return_final_state else y


def _m1_core_fwd(uf, df, Af, Bf, Cf, h0, interpret, return_final_state):
    out = _m1_core(uf, df, Af, Bf, Cf, h0, interpret, return_final_state)
    return out, (uf, df, Af, Bf, Cf, h0)


def _m1_core_bwd(interpret, return_final_state, res, ct):
    """Pallas backward (see the backward section above)."""
    uf, df, Af, Bf, Cf, h0 = res
    dy, dfinal = ct if return_final_state else (ct, None)
    du, ddt, dAf, dBf, dCf, dh0 = _m1_pallas_bwd_impl(
        uf, df, Af, Bf, Cf, dy.astype(jnp.float32), interpret,
        h0=h0, dfinal=dfinal,
    )
    return du, ddt, dAf, dBf, dCf, dh0


_m1_core.defvjp(_m1_core_fwd, _m1_core_bwd)


def selective_scan_pallas(
    u: jax.Array,
    delta: jax.Array,
    A: jax.Array,
    B: jax.Array,
    C: jax.Array,
    D: jax.Array | None = None,
    z: jax.Array | None = None,
    delta_bias: jax.Array | None = None,
    delta_softplus: bool = False,
    initial_state: jax.Array | None = None,
    return_final_state: bool = False,
    interpret: bool | None = None,
):
    """Drop-in for ops/scan.selective_scan backed by the Pallas kernel.

    Every path — plain training, seeded (``initial_state``: decode
    prefill / SP shards), and ``return_final_state`` — runs under the
    custom VJP whose backward is itself Pallas: the entry-state
    recompute starts from the same seed, a final-state cotangent seeds
    the reverse sweep, and the initial-state gradient is returned.
    ``interpret=None`` auto-selects the Pallas interpreter off-TPU (CPU
    tests run the same kernel code).

    The channel axis is padded to a multiple of the 128-lane vreg width
    and t to a multiple of 8 sublanes, so Mosaic only ever sees aligned
    BlockSpecs; the padding is numerically inert (u=dt=A=0 channels/steps
    carry zero state and are sliced off), autodiff handles the pad/slice,
    and interpret mode takes the identical path so CPU tests exercise it.
    """
    interpret = resolve_interpret(interpret)

    b, t, d = u.shape
    uf, df, Af, Bf, Cf, Df = _prep(u, delta, A, B, C, D, delta_bias, delta_softplus)

    pad_d = -d % 128
    pad_t = -t % 8
    if pad_d or pad_t:
        pt, pd = (0, pad_t), (0, pad_d)
        uf = jnp.pad(uf, ((0, 0), pt, pd))
        df = jnp.pad(df, ((0, 0), pt, pd))
        Af = jnp.pad(Af, (pd, (0, 0)))
        Bf = jnp.pad(Bf, ((0, 0), pt, (0, 0)))
        Cf = jnp.pad(Cf, ((0, 0), pt, (0, 0)))

    h0 = (
        jnp.zeros((b, d + pad_d, Af.shape[-1]), jnp.float32)
        if initial_state is None
        else initial_state.astype(jnp.float32)
    )
    if pad_d and initial_state is not None:
        h0 = jnp.pad(h0, ((0, 0), (0, pad_d), (0, 0)))
    out = _m1_core(uf, df, Af, Bf, Cf, h0, interpret, return_final_state)
    if return_final_state:
        y, h_last = out
        if pad_d:
            h_last = h_last[:, :d]
    else:
        y, h_last = out, None

    if pad_d or pad_t:
        y = y[:, :t, :d]
        uf = uf[:, :t, :d]

    if Df is not None:
        y = y + uf * Df[None, None, :]
    if z is not None:
        y = y * jax.nn.silu(z.astype(jnp.float32))
    y = y.astype(u.dtype)
    if return_final_state:
        return y, h_last
    return y
