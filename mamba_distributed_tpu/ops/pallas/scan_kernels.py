"""Pallas selective-scan (Mamba-1) kernel.

TPU-native counterpart of the reference dependency's CUDA selective scan
(``mamba_ssm/csrc/selective_scan/`` in mamba-ssm 2.2.2) — re-derived for
the VPU/VMEM model rather than translated:

  * grid = (batch, d-blocks, t-tiles); the recurrent state lives in a VMEM
    scratch laid out ``(n, d_blk)`` (a (16, 128)-lane vreg tile is exactly
    one state update's working set) and is carried across the sequential
    t-tile dimension, so arbitrarily long sequences stream through a
    bounded VMEM budget;
  * the time loop is sequential *inside* the kernel (the recurrence is
    sequential; the CUDA kernel does the same) — HBM traffic is just
    u/delta in, y out: nothing of shape (b, t, d, n) ever exists, unlike
    the XLA associative-scan path whose per-chunk intermediates are remat
    tricks around exactly that tensor;
  * batch and d-block grid dimensions are marked parallel (megacore);
    state math is fp32 like the CUDA kernel.

Training uses ``jax.custom_vjp``: the backward runs the chunked
associative-scan formulation (ops/scan.selective_scan; same math, XLA
autodiff), so gradients are identical to the XLA path — pinned by
tests/test_pallas.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mamba_distributed_tpu.ops.scan import _prep


def _m1_scan_kernel(
    u_ref, dt_ref, At_ref, B_ref, C_ref, h0_ref, y_ref, hT_ref, h_scratch,
    *, nt: int
):
    """Sequential selective scan for one (batch, d-block, t-tile) cell.

    u/dt (1, tb, dblk) fp32; At (n, dblk); B/C (1, tb, n); h0 (1, n, dblk).
    The state is carried across t-tiles in ``h_scratch`` (n, dblk); the
    final tile writes it to hT (1, n, dblk).
    """
    ti = pl.program_id(2)

    @pl.when(ti == 0)
    def _():
        h_scratch[...] = h0_ref[0]

    At = At_ref[...]          # (n, dblk)
    tb = u_ref.shape[1]

    def body(i, h):
        dt_t = dt_ref[0, pl.ds(i, 1)]              # (1, dblk)
        u_t = u_ref[0, pl.ds(i, 1)]                # (1, dblk)
        Bn = B_ref[0, pl.ds(i, 1)].reshape(-1, 1)  # (n, 1)
        Cn = C_ref[0, pl.ds(i, 1)].reshape(-1, 1)  # (n, 1)
        h = h * jnp.exp(At * dt_t) + (dt_t * u_t) * Bn
        y_ref[0, pl.ds(i, 1)] = jnp.sum(h * Cn, axis=0, keepdims=True)
        return h

    h_scratch[...] = jax.lax.fori_loop(0, tb, body, h_scratch[...])

    @pl.when(ti == nt - 1)
    def _():
        hT_ref[0] = h_scratch[...]


def _divisor_up_to(x: int, target: int) -> int:
    """Largest divisor of x that is <= target."""
    blk = min(x, target)
    while x % blk != 0:
        blk -= 1
    return blk


def _pick_blocks(t: int, d: int) -> tuple[int, int]:
    """(t_blk, dblk) dividing (t, d), sized for a few-MB VMEM footprint.

    dblk targets 512 lanes (a multiple of the 128-lane vreg width when d
    allows); t_blk then caps the u/dt/y tiles at ~2 MB each in fp32.
    """
    for cand in (512, 256, 128):
        if d % cand == 0:
            dblk = cand
            break
    else:
        dblk = _divisor_up_to(d, 512)
    t_target = max(1, (2 << 20) // (4 * dblk))  # ~2 MB fp32 per (tb, dblk) tile
    t_blk = _divisor_up_to(t, min(t, t_target))
    return t_blk, dblk


def _m1_pallas_fwd(uf, df, Af, Bf, Cf, h0, interpret):
    """fp32 core: (b,t,d)x2, (d,n), (b,t,n)x2, (b,d,n) -> y, final_state."""
    b, t, d = uf.shape
    n = Af.shape[-1]
    t_blk, dblk = _pick_blocks(t, d)
    nt = t // t_blk
    grid = (b, d // dblk, nt)

    io_spec = pl.BlockSpec((1, t_blk, dblk), lambda bi, di, ti: (bi, ti, di))
    bc_spec = pl.BlockSpec((1, t_blk, n), lambda bi, di, ti: (bi, ti, 0))
    st_spec = pl.BlockSpec((1, n, dblk), lambda bi, di, ti: (bi, 0, di))

    y, hT = pl.pallas_call(
        functools.partial(_m1_scan_kernel, nt=nt),
        grid=grid,
        in_specs=[
            io_spec,
            io_spec,
            pl.BlockSpec((n, dblk), lambda bi, di, ti: (0, di)),
            bc_spec,
            bc_spec,
            st_spec,
        ],
        out_specs=[io_spec, st_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, d), jnp.float32),
            jax.ShapeDtypeStruct((b, n, d), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((n, dblk), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(uf, df, Af.T, Bf, Cf, jnp.swapaxes(h0, 1, 2))
    return y, jnp.swapaxes(hT, 1, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _m1_core(uf, df, Af, Bf, Cf, interpret):
    b, _, d = uf.shape
    h0 = jnp.zeros((b, d, Af.shape[-1]), jnp.float32)
    y, _ = _m1_pallas_fwd(uf, df, Af, Bf, Cf, h0, interpret)
    return y


def _m1_core_fwd(uf, df, Af, Bf, Cf, interpret):
    return _m1_core(uf, df, Af, Bf, Cf, interpret), (uf, df, Af, Bf, Cf)


def _m1_core_bwd(interpret, res, dy):
    """Backward through the chunked associative-scan formulation."""
    from mamba_distributed_tpu.ops.scan import selective_scan

    uf, df, Af, Bf, Cf = res

    def f(u, dt, A, B, C):
        # inputs are already fp32 + softplus-ed; no D/z (applied outside)
        return selective_scan(u, dt, A, B, C)

    _, vjp = jax.vjp(f, uf, df, Af, Bf, Cf)
    return vjp(dy.astype(jnp.float32))


_m1_core.defvjp(_m1_core_fwd, _m1_core_bwd)


def selective_scan_pallas(
    u: jax.Array,
    delta: jax.Array,
    A: jax.Array,
    B: jax.Array,
    C: jax.Array,
    D: jax.Array | None = None,
    z: jax.Array | None = None,
    delta_bias: jax.Array | None = None,
    delta_softplus: bool = False,
    initial_state: jax.Array | None = None,
    return_final_state: bool = False,
    interpret: bool | None = None,
):
    """Drop-in for ops/scan.selective_scan backed by the Pallas kernel.

    With ``initial_state``/``return_final_state`` (decode prefill / SP)
    the non-custom-vjp path runs; the plain training path gets the custom
    VJP with an XLA backward.  ``interpret=None`` auto-selects the Pallas
    interpreter off-TPU (CPU tests run the same kernel code).
    """
    if interpret is None:
        kind = getattr(jax.devices()[0], "device_kind", "").lower()
        interpret = not (jax.default_backend() == "tpu" or "tpu" in kind)

    b, t, d = u.shape
    uf, df, Af, Bf, Cf, Df = _prep(u, delta, A, B, C, D, delta_bias, delta_softplus)

    if initial_state is None and not return_final_state:
        y = _m1_core(uf, df, Af, Bf, Cf, interpret)
        h_last = None
    else:
        h0 = (
            jnp.zeros((b, d, Af.shape[-1]), jnp.float32)
            if initial_state is None
            else initial_state.astype(jnp.float32)
        )
        y, h_last = _m1_pallas_fwd(uf, df, Af, Bf, Cf, h0, interpret)

    if Df is not None:
        y = y + uf * Df[None, None, :]
    if z is not None:
        y = y * jax.nn.silu(z.astype(jnp.float32))
    y = y.astype(u.dtype)
    if return_final_state:
        return y, h_last
    return y
