"""Shared Pallas-kernel plumbing."""

from __future__ import annotations

import os

import jax


def resolve_interpret(interpret: bool | None) -> bool:
    """Resolve the ``interpret=None`` auto-default for a pallas_call.

    Auto picks the real Mosaic lowering on TPU (including tunneled
    platforms whose backend name isn't "tpu") and the Pallas interpreter
    elsewhere, so CPU tests run the same kernel code.  The
    ``MDT_PALLAS_INTERPRET`` env var ("0"/"1") overrides auto-detection —
    lowering tests set it to "0" to force the real Mosaic path through
    *composed* graphs (models, shard_map) that never see an ``interpret``
    argument.
    """
    if interpret is not None:
        return interpret
    env = os.environ.get("MDT_PALLAS_INTERPRET")
    if env is not None:
        return env != "0"
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    return not (jax.default_backend() == "tpu" or "tpu" in kind)
