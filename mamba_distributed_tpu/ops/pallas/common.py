"""Shared Pallas-kernel plumbing."""

from __future__ import annotations

import os

import jax
from jax.experimental.pallas import tpu as _pltpu

# jax renamed TPUCompilerParams -> CompilerParams across releases; every
# kernel module takes the alias from here so importing any one of them
# works on either API, in any import order (the tier-1 quirk where
# tests/test_attention_pallas.py only passed under the full suite came
# from ssd_kernels failing this lookup at import time).
CompilerParams = getattr(_pltpu, "CompilerParams", None) or getattr(
    _pltpu, "TPUCompilerParams"
)


def resolve_interpret(interpret: bool | None) -> bool:
    """Resolve the ``interpret=None`` auto-default for a pallas_call.

    Auto picks the real Mosaic lowering on TPU (including tunneled
    platforms whose backend name isn't "tpu") and the Pallas interpreter
    elsewhere, so CPU tests run the same kernel code.  The
    ``MDT_PALLAS_INTERPRET`` env var ("0"/"1") overrides auto-detection —
    lowering tests set it to "0" to force the real Mosaic path through
    *composed* graphs (models, shard_map) that never see an ``interpret``
    argument.
    """
    if interpret is not None:
        return interpret
    env = os.environ.get("MDT_PALLAS_INTERPRET")
    if env is not None:
        return env != "0"
    return not on_tpu()


def on_tpu() -> bool:
    """True when the default backend is a TPU (tunneled platforms whose
    backend name isn't "tpu" are detected via device_kind)."""
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    return jax.default_backend() == "tpu" or "tpu" in kind


def resolve_attn_impl(impl: str) -> str:
    """Resolve the ``attn_impl="auto"`` config default.

    On TPU hardware the Pallas flash kernels measured +12% train
    throughput over the blockwise-XLA SDPA on the hybrid-280m preset
    (round-4 sweep, MEASUREMENTS.md), so auto picks "pallas" there; on
    CPU (tests, debugging) auto picks "xla" to avoid paying for the
    Pallas interpreter in composed graphs.

    ``MDT_ATTN_IMPL`` ("xla" | "pallas") overrides the probe directly and
    keeps the env contract single-purpose (ADVICE r4: overloading
    ``MDT_PALLAS_INTERPRET`` here was easy to misread).  Failing that,
    ``MDT_PALLAS_INTERPRET`` still steers auto for backwards
    compatibility — note the asymmetry: env=1 means "interpret Pallas
    kernels" for ``resolve_interpret`` but resolves *attention* to the
    XLA path, so ssm_impl="pallas" + attn_impl="auto" under env=1 runs
    interpreted SSM kernels next to XLA attention.  "0" (the chip-free
    ``jax.export`` TPU-lowering pattern) resolves auto to "pallas" so
    CPU-host exports targeting TPU bake in the kernels they'd get on
    hardware.
    """
    if impl != "auto":
        return impl
    env = os.environ.get("MDT_ATTN_IMPL")
    if env is not None:
        if env not in ("xla", "pallas"):
            raise ValueError(f"MDT_ATTN_IMPL must be xla|pallas, got {env!r}")
        return env
    env = os.environ.get("MDT_PALLAS_INTERPRET")
    if env is not None:
        return "xla" if env != "0" else "pallas"
    return "pallas" if on_tpu() else "xla"
