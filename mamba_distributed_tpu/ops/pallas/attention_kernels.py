"""Pallas flash-attention kernel (causal, GQA) for the hybrid layers.

TPU-native counterpart of the flash-attn CUDA kernels the reference's
attention surface sits on one dep down (``mamba_ssm.modules.mha.MHA`` →
``flash_attn`` — mamba-ssm 2.2.2; the reference never enables attention,
SURVEY.md §2.3, but BASELINE config 5 requires it).  Re-derived for the
MXU/VMEM model, not translated:

  * grid = (batch, q-head, q-block, kv-block); the kv-block dimension is
    the sequential one — the online-softmax accumulator (running max,
    denominator, output) lives in VMEM scratch and streams KV through a
    bounded working set, exactly the flash construction;
  * fully-future (q-block, kv-block) pairs are *skipped* via ``pl.when``
    on the grid indices — unlike the XLA blockwise path
    (ops/blockwise_attention.py) whose branch-free schedule computes and
    masks them, the kernel recovers the ~2x causal FLOPs;
  * GQA routes the shared KV head via BlockSpec index maps
    (``hi // rep``) — Q heads never see repeated KV in HBM;
  * softmax statistics are carried per q-row in fp32; the row
    log-sum-exp is emitted in a lane-degenerate ``(..., tq, 8)`` layout
    (block spans the full trailing dim, so Mosaic tiling stays legal
    without transposing row statistics into lanes).

The backward is Pallas too (the flash-attn backward's trade): p is
recomputed per (q, kv) block pair from q/k and the saved row-lse — no
(t, t) tensor is ever materialized — with one kernel accumulating dq
over the sequential kv dimension and a second accumulating dk/dv over
the sequential q dimension; per-q-head dk/dv partials are group-summed
in XLA (same pattern as the SSD backward's dB/dC).  Gradient parity vs
the XLA blockwise path is pinned by tests/test_attention_pallas.py.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mamba_distributed_tpu.ops.pallas.common import (
    CompilerParams,
    resolve_interpret,
)

_NEG_INF = float("-inf")


def _pick_block(t: int, target: int) -> int:
    """Block size for a (padded) sequence length: target, or all of t."""
    if t >= target:
        return target
    return -(-t // 8) * 8  # round up to the 8-sublane granule


def _causal_mask(qb, kb, q0, k0, tk_valid):
    """(qb, kb) bool: query row q0+i may attend key col k0+j (< tk_valid)."""
    qpos = jax.lax.broadcasted_iota(jnp.int32, (qb, kb), 0) + q0
    kpos = jax.lax.broadcasted_iota(jnp.int32, (qb, kb), 1) + k0
    return (qpos >= kpos) & (kpos < tk_valid)


def _fa_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, den_scr, acc_scr,
    *, nk: int, sm_scale: float, offset: int, tk_valid: int,
):
    """One (batch, q-head, q-block, kv-block) cell of the forward."""
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    qb = q_ref.shape[2]
    kb = k_ref.shape[2]

    @pl.when(kj == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        den_scr[...] = jnp.zeros_like(den_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # skip fully-future blocks: first key of this block vs last query row
    @pl.when(kj * kb <= qi * qb + qb - 1 + offset)
    def _():
        q = q_ref[0, 0]                                  # (qb, hd)
        s = jax.lax.dot_general(                         # (qb, kb) fp32
            q, k_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        mask = _causal_mask(qb, kb, qi * qb + offset, kj * kb, tk_valid)
        s = jnp.where(mask, s, _NEG_INF)

        # lanes of the stat scratches hold replicated copies; a lane-max
        # read avoids ref lane-slicing (no Mosaic sub-128 memref slices)
        m_prev = jnp.max(m_scr[...], axis=1, keepdims=True)   # (qb, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # rows with every key masked so far keep m = -inf; guard both exps
        # (values are finite or -inf, never NaN/+inf, so `> -inf` stands in
        # for isfinite — which this jax's Mosaic lowering lacks)
        scale = jnp.where(m_prev > _NEG_INF, jnp.exp(m_prev - m_new), 0.0)
        p = jnp.where(s > _NEG_INF, jnp.exp(s - m_new), 0.0)      # (qb, kb)

        acc_scr[...] = acc_scr[...] * scale + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        den_scr[...] = den_scr[...] * scale + jnp.sum(p, axis=1, keepdims=True)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(kj == nk - 1)
    def _():
        den = jnp.max(den_scr[...], axis=1, keepdims=True)    # (qb, 1)
        o_ref[0, 0] = (acc_scr[...] / jnp.maximum(den, 1e-30)).astype(
            o_ref.dtype
        )
        # row lse; rows that saw no unmasked key (possible only for
        # offset < 0 uses) get +inf so the backward's exp(s - lse) is 0
        # there.  Padded query rows attend normally and get a finite lse —
        # their backward is harmless because their dO rows are zero.
        m_fin = jnp.max(m_scr[...], axis=1, keepdims=True)
        lse = jnp.where(
            den > 0.0, m_fin + jnp.log(jnp.maximum(den, 1e-30)),
            jnp.inf,
        )
        lse_ref[0, 0] = jnp.broadcast_to(lse, (lse.shape[0], 8))


def _fa_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref, dq_scr,
    *, nk: int, sm_scale: float, offset: int, tk_valid: int,
):
    """dq for one q-block, accumulated over the sequential kv dimension."""
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    qb = q_ref.shape[2]
    kb = k_ref.shape[2]

    @pl.when(kj == 0)
    def _():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(kj * kb <= qi * qb + qb - 1 + offset)
    def _():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        mask = _causal_mask(qb, kb, qi * qb + offset, kj * kb, tk_valid)
        s = jnp.where(mask, s, _NEG_INF)
        # stat blocks carry lane-replicated values; lane-max reads avoid
        # sub-128 vector lane slices (Mosaic-safe)
        lse = jnp.max(lse_ref[0, 0], axis=1, keepdims=True)   # (qb, 1)
        p = jnp.exp(s - lse)                             # (qb, kb)
        dp = jax.lax.dot_general(                        # dO @ V^T
            do_ref[0, 0], v_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dlt = jnp.max(dlt_ref[0, 0], axis=1, keepdims=True)
        ds = p * (dp - dlt)
        dq_scr[...] = dq_scr[...] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale

    @pl.when(kj == nk - 1)
    def _():
        dq_ref[0, 0] = dq_scr[...]


def _fa_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dk_ref, dv_ref,
    dk_scr, dv_scr, *, nq: int, sm_scale: float, offset: int, tk_valid: int,
):
    """Per-q-head dk/dv partials for one kv-block, over the sequential
    q dimension (group-summed over GQA reps in XLA afterwards)."""
    kj = pl.program_id(2)
    qi = pl.program_id(3)
    qb = q_ref.shape[2]
    kb = k_ref.shape[2]

    @pl.when(qi == 0)
    def _():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(kj * kb <= qi * qb + qb - 1 + offset)
    def _():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        mask = _causal_mask(qb, kb, qi * qb + offset, kj * kb, tk_valid)
        s = jnp.where(mask, s, _NEG_INF)
        # stat blocks carry lane-replicated values; lane-max reads avoid
        # sub-128 vector lane slices (Mosaic-safe)
        lse = jnp.max(lse_ref[0, 0], axis=1, keepdims=True)   # (qb, 1)
        p = jnp.exp(s - lse)                             # (qb, kb)
        do = do_ref[0, 0]
        # dV += P^T @ dO   (contract the q/sublane dim of both)
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dlt = jnp.max(dlt_ref[0, 0], axis=1, keepdims=True)
        ds = p * (dp - dlt)
        # dK += dS^T @ Q
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0, 0] = dk_scr[...]
        dv_ref[0, 0] = dv_scr[...]


def _fa_fwd_impl(qt, kt, vt, offset, tk_valid, qb, kb, interpret):
    """(b, nh, tq, hd), (b, nkv, tk, hd) -> o (b, nh, tq, hd), lse."""
    b, nh, tq, hd = qt.shape
    nkv, tk = kt.shape[1], kt.shape[2]
    rep = nh // nkv
    nq, nk = tq // qb, tk // kb
    sm_scale = 1.0 / math.sqrt(hd)
    grid = (b, nh, nq, nk)

    q_spec = pl.BlockSpec((1, 1, qb, hd), lambda bi, hi, qi, kj: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, kb, hd), lambda bi, hi, qi, kj: (bi, hi // rep, kj, 0)
    )
    lse_spec = pl.BlockSpec((1, 1, qb, 8), lambda bi, hi, qi, kj: (bi, hi, qi, 0))

    o, lse = pl.pallas_call(
        functools.partial(
            _fa_fwd_kernel, nk=nk, sm_scale=sm_scale, offset=offset,
            tk_valid=tk_valid,
        ),
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[q_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, nh, tq, hd), qt.dtype),
            jax.ShapeDtypeStruct((b, nh, tq, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((qb, 128), jnp.float32),
            pltpu.VMEM((qb, 128), jnp.float32),
            pltpu.VMEM((qb, hd), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(qt, kt, vt)
    return o, lse


def _fa_bwd_dq_call(qt, kt, vt, do, lse, dlt, offset, tk_valid, qb, kb,
                    interpret):
    """Pair-level dq (b, nh, tq, hd) fp32 given row lse/delta in the
    lane-degenerate (..., 8) layout.  Reused per ring-attention hop."""
    b, nh, tq, hd = qt.shape
    nkv, tk = kt.shape[1], kt.shape[2]
    rep = nh // nkv
    nq, nk = tq // qb, tk // kb
    sm_scale = 1.0 / math.sqrt(hd)

    q_spec = pl.BlockSpec((1, 1, qb, hd), lambda bi, hi, qi, kj: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, kb, hd), lambda bi, hi, qi, kj: (bi, hi // rep, kj, 0)
    )
    lse_spec = pl.BlockSpec((1, 1, qb, 8), lambda bi, hi, qi, kj: (bi, hi, qi, 0))
    seq_kv = CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
    )

    return pl.pallas_call(
        functools.partial(
            _fa_bwd_dq_kernel, nk=nk, sm_scale=sm_scale, offset=offset,
            tk_valid=tk_valid,
        ),
        grid=(b, nh, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, lse_spec, lse_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, nh, tq, hd), jnp.float32),
        scratch_shapes=[pltpu.VMEM((qb, hd), jnp.float32)],
        compiler_params=seq_kv,
        interpret=interpret,
    )(qt, kt, vt, do, lse, dlt)


def _fa_bwd_dkv_call(qt, kt, vt, do, lse, dlt, offset, tk_valid, qb, kb,
                     interpret):
    """Pair-level (dk, dv) (b, nkv, tk, hd) fp32, GQA group-summed."""
    b, nh, tq, hd = qt.shape
    nkv, tk = kt.shape[1], kt.shape[2]
    rep = nh // nkv
    nq, nk = tq // qb, tk // kb
    sm_scale = 1.0 / math.sqrt(hd)
    seq_kv = CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
    )

    # grid loops kv blocks in the third slot, q blocks sequential
    rq_spec = pl.BlockSpec((1, 1, qb, hd), lambda bi, hi, kj, qi: (bi, hi, qi, 0))
    rkv_spec = pl.BlockSpec(
        (1, 1, kb, hd), lambda bi, hi, kj, qi: (bi, hi // rep, kj, 0)
    )
    rkv_out = pl.BlockSpec((1, 1, kb, hd), lambda bi, hi, kj, qi: (bi, hi, kj, 0))
    rlse_spec = pl.BlockSpec((1, 1, qb, 8), lambda bi, hi, kj, qi: (bi, hi, qi, 0))
    dk_part, dv_part = pl.pallas_call(
        functools.partial(
            _fa_bwd_dkv_kernel, nq=nq, sm_scale=sm_scale, offset=offset,
            tk_valid=tk_valid,
        ),
        grid=(b, nh, nk, nq),
        in_specs=[rq_spec, rkv_spec, rkv_spec, rq_spec, rlse_spec, rlse_spec],
        out_specs=[rkv_out, rkv_out],
        out_shape=[
            jax.ShapeDtypeStruct((b, nh, tk, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, nh, tk, hd), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((kb, hd), jnp.float32),
            pltpu.VMEM((kb, hd), jnp.float32),
        ],
        compiler_params=seq_kv,
        interpret=interpret,
    )(qt, kt, vt, do, lse, dlt)

    # GQA group-sum of the per-q-head partials (rep == 1 is a no-op reshape)
    dk = jnp.sum(dk_part.reshape(b, nkv, rep, tk, hd), axis=2)
    dv = jnp.sum(dv_part.reshape(b, nkv, rep, tk, hd), axis=2)
    return dk, dv


def lane8(x):
    """(..., t) row statistic -> the kernels' lane-degenerate (..., t, 8)."""
    return jnp.broadcast_to(x[..., None], (*x.shape, 8))


def _fa_bwd_impl(qt, kt, vt, o, lse, do, offset, tk_valid, qb, kb, interpret):
    # D_i = rowsum(dO ⊙ O), emitted in the same lane-degenerate layout as
    # lse (elementwise + lane reduction: XLA fuses it)
    dlt = lane8(jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
    ))
    dq = _fa_bwd_dq_call(qt, kt, vt, do, lse, dlt, offset, tk_valid, qb, kb,
                         interpret)
    dk, dv = _fa_bwd_dkv_call(qt, kt, vt, do, lse, dlt, offset, tk_valid,
                              qb, kb, interpret)
    return dq, dk, dv


def flash_pair_fwd(qt, kt, vt, offset, qb=256, kb=256, interpret=None):
    """Raw pair forward: (o (b, nh, tq, hd), lse (b, nh, tq) fp32).

    Head-major layouts, NOT differentiable on its own — ring attention
    (parallel/ring_attention.py) composes these pair calls under its own
    custom_vjp, merging per-hop (o, lse) partials and reusing
    ``flash_pair_dq``/``flash_pair_dkv`` with the GLOBAL lse in the
    backward (the flash decomposition is exact per (q, kv) pair given
    the merged lse and delta).  ``offset`` must be static: ring hops are
    fully-past (offset = tq), diagonal (0), or skipped.
    """
    interpret = resolve_interpret(interpret)
    tq, tk = qt.shape[2], kt.shape[2]
    qb = _pick_block(tq, qb)
    kb = _pick_block(tk, kb)
    pad_q, pad_k = -tq % qb, -tk % kb
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    o, lse8 = _fa_fwd_impl(qt, kt, vt, int(offset), tk, qb, kb, interpret)
    return o[:, :, :tq], lse8[:, :, :tq, 0]


def flash_pair_dq(qt, kt, vt, do, lse, dlt, offset, qb=256, kb=256,
                  interpret=None):
    """Raw pair dq (fp32) from the GLOBAL row lse / delta (b, nh, tq)."""
    interpret = resolve_interpret(interpret)
    tq, tk = qt.shape[2], kt.shape[2]
    qb = _pick_block(tq, qb)
    kb = _pick_block(tk, kb)
    pad_q, pad_k = -tq % qb, -tk % kb
    pads = ((0, 0), (0, 0), (0, pad_q), (0, 0))
    if pad_q:
        qt, do = jnp.pad(qt, pads), jnp.pad(do, pads)
        lse = jnp.pad(lse, ((0, 0), (0, 0), (0, pad_q)),
                      constant_values=jnp.inf)
        dlt = jnp.pad(dlt, ((0, 0), (0, 0), (0, pad_q)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    dq = _fa_bwd_dq_call(qt, kt, vt, do, lane8(lse), lane8(dlt),
                         int(offset), tk, qb, kb, interpret)
    return dq[:, :, :tq]


def flash_pair_dkv(qt, kt, vt, do, lse, dlt, offset, qb=256, kb=256,
                   interpret=None):
    """Raw pair (dk, dv) (fp32, GQA group-summed) from GLOBAL lse/delta."""
    interpret = resolve_interpret(interpret)
    tq, tk = qt.shape[2], kt.shape[2]
    qb = _pick_block(tq, qb)
    kb = _pick_block(tk, kb)
    pad_q, pad_k = -tq % qb, -tk % kb
    pads = ((0, 0), (0, 0), (0, pad_q), (0, 0))
    if pad_q:
        qt, do = jnp.pad(qt, pads), jnp.pad(do, pads)
        lse = jnp.pad(lse, ((0, 0), (0, 0), (0, pad_q)),
                      constant_values=jnp.inf)
        dlt = jnp.pad(dlt, ((0, 0), (0, 0), (0, pad_q)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    dk, dv = _fa_bwd_dkv_call(qt, kt, vt, do, lane8(lse), lane8(dlt),
                              int(offset), tk, qb, kb, interpret)
    return dk[:, :, :tk], dv[:, :, :tk]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fa_core(qt, kt, vt, offset, tk_valid, qb, kb, interpret):
    o, _ = _fa_fwd_impl(qt, kt, vt, offset, tk_valid, qb, kb, interpret)
    return o


def _fa_core_fwd(qt, kt, vt, offset, tk_valid, qb, kb, interpret):
    o, lse = _fa_fwd_impl(qt, kt, vt, offset, tk_valid, qb, kb, interpret)
    return o, (qt, kt, vt, o, lse)


def _fa_core_bwd(offset, tk_valid, qb, kb, interpret, res, do):
    qt, kt, vt, o, lse = res
    dq, dk, dv = _fa_bwd_impl(
        qt, kt, vt, o, lse, do, offset, tk_valid, qb, kb, interpret
    )
    return (
        dq.astype(qt.dtype), dk.astype(kt.dtype), dv.astype(vt.dtype)
    )


_fa_core.defvjp(_fa_core_fwd, _fa_core_bwd)


def flash_sdpa_causal(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    offset: int = 0,
    q_block: int = 256,
    k_block: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Causal softmax(QK^T/sqrt(d))V with GQA broadcast — Pallas flash.

    Same contract as ops/blockwise_attention.blockwise_sdpa_causal:
    q (b, tq, nh, hd); k/v (b, tk, nkv, hd); ``offset`` = absolute
    position of q[0] minus that of k[0] (static).  Sequence lengths are
    padded to block multiples (padded keys are masked via the key-length
    term; padded query rows are computed then sliced off — their
    cotangent rows are zero through the pad/slice pair, so ds vanishes
    on them and the backward stays NaN-free), head dims pass through
    whole (blocks span the full trailing dim).  ``interpret=None``
    auto-selects the Pallas interpreter off-TPU.
    """
    interpret = resolve_interpret(interpret)
    b, tq, nh, hd = q.shape
    tk, nkv = k.shape[1], k.shape[2]
    if nh % nkv:
        raise ValueError(f"num_heads {nh} not a multiple of kv heads {nkv}")
    offset = int(offset)

    qb = _pick_block(tq, q_block)
    kb = _pick_block(tk, k_block)
    pad_q = -tq % qb
    pad_k = -tk % kb

    qt = jnp.moveaxis(q, 2, 1)
    kt = jnp.moveaxis(k, 2, 1)
    vt = jnp.moveaxis(v, 2, 1)
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))

    o = _fa_core(qt, kt, vt, offset, tk, qb, kb, interpret)
    if pad_q:
        o = o[:, :, :tq]
    return jnp.moveaxis(o, 1, 2)


# ---------------------------------------------------------------------------
# Ragged paged decode attention ("Ragged Paged Attention: A High-Performance
# and Flexible LLM Inference Kernel for TPU", PAPERS.md).
#
# Serving decode over the paged KV pool (models/attention.py): each row of
# the slot batch sits at its OWN position, its KV scattered across pool
# pages named by its page-table row.  The kernel walks each row's page
# list with the table scalar-prefetched (the BlockSpec index map picks the
# physical page per grid step, so no (S, W*page) gather ever exists) and
# skips every page at or past the row's kv_len via ``pl.when`` — decode
# FLOPs track live tokens, not pool capacity.  Grid (slots, kv-heads,
# pages), pages sequential; the online-softmax accumulator lives in VMEM
# scratch exactly like the flash forward above.
# ---------------------------------------------------------------------------

# python-side-effect trace counters (one bump per jit trace): the whole
# point of the fixed (S, W) layout is that occupancy/length changes never
# retrace — tests/test_paged_attention.py pins both.
TRACE_COUNTS = {"ragged_decode": 0, "ragged_prefill": 0}


def _rpa_kernel(
    tbl_ref, len_ref, *rest,
    nw: int, pg: int, sm_scale: float, quant: bool = False,
):
    """One (slot, kv-head, page) cell of the ragged decode forward.

    ``quant`` (int8 page pools): two extra scalar-prefetched (P, nkv)
    f32 scale arrays ride between the metadata and the tensor refs; the
    page tile is read as int8 and dequantized IN-REGISTER — the K
    scale folds into the score block's scalar multiply, the V scale
    into the accumulator update — one scalar each per (page, head)
    cell, no dequantized page ever materializes in VMEM.
    """
    if quant:
        ks_ref, vs_ref, q_ref, k_ref, v_ref, o_ref, m_scr, den_scr, \
            acc_scr = rest
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, den_scr, acc_scr = rest
    s = pl.program_id(0)
    h = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        den_scr[...] = jnp.zeros_like(den_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    kv_len = len_ref[s]

    # whole pages at/past the row's length are SKIPPED, not masked —
    # the ragged saving (a dead row, kv_len == 0, skips everything)
    @pl.when(j * pg < kv_len)
    def _():
        q = q_ref[0, 0]                                  # (R8, hd)
        k = k_ref[0, 0]                                  # (pg, hd)
        if quant:
            phys = tbl_ref[s, j]
            # int8 tile -> fp32 dot; the per-(page, head) K scale is a
            # SCALAR for the whole block, folded into the score scale
            scores = jax.lax.dot_general(
                q.astype(jnp.float32), k.astype(jnp.float32),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * (ks_ref[phys, h] * sm_scale)
        else:
            scores = jax.lax.dot_general(                # (R8, pg) fp32
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale
        kpos = jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1
        ) + j * pg
        scores = jnp.where(kpos < kv_len, scores, _NEG_INF)

        # lane-replicated row stats; lane-max reads (no sub-128 slices)
        m_prev = jnp.max(m_scr[...], axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        scale = jnp.where(m_prev > _NEG_INF, jnp.exp(m_prev - m_new), 0.0)
        p = jnp.where(scores > _NEG_INF, jnp.exp(scores - m_new), 0.0)

        v = v_ref[0, 0]                                  # (pg, hd)
        if quant:
            # V dequant: one scalar multiply on the fp32 accumulator
            acc_scr[...] = acc_scr[...] * scale + jax.lax.dot_general(
                p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * vs_ref[tbl_ref[s, j], h]
        else:
            acc_scr[...] = acc_scr[...] * scale + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        den_scr[...] = den_scr[...] * scale + jnp.sum(
            p, axis=1, keepdims=True
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(j == nw - 1)
    def _():
        den = jnp.max(den_scr[...], axis=1, keepdims=True)
        # rows with no live page (kv_len == 0) emit zeros, not NaN
        o_ref[0, 0] = (acc_scr[...] / jnp.maximum(den, 1e-30)).astype(
            o_ref.dtype
        )


def ragged_paged_decode_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    kv_len: jax.Array,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Paged decode attention with per-row lengths.

    q (S, nh, hd) — one query token per slot; k_pages/v_pages
    (P, nkv, page, hd) — the shared HEAD-MAJOR page pool (page 0 =
    trash); page_table (S, W) int32; kv_len (S,) int32 — tokens readable
    per row (INCLUDING any token written this step).  Returns
    (S, nh, hd).

    ``k_scale``/``v_scale`` (int8 pools: (P, nkv) f32, one symmetric
    scale per (physical page, kv head)) ride the scalar-prefetch channel
    next to the page table, and the kernel dequantizes each visited
    int8 tile in-register — the per-page scalar folds into the score
    multiply (K) and the accumulator update (V), so page-walk HBM
    traffic is the int8 bytes and nothing widened ever round-trips.

    Numerics match the lax fallback (gather + masked SDPA,
    models/attention._sdpa_positions; int8: dequantizing gather) to fp
    tolerance; one jit trace covers every occupancy / length mix at a
    fixed (S, W) layout (``TRACE_COUNTS["ragged_decode"]``).
    ``interpret=None`` auto-selects the Pallas interpreter off-TPU.
    """
    interpret = resolve_interpret(interpret)
    TRACE_COUNTS["ragged_decode"] += 1
    quant = k_scale is not None
    S, nh, hd = q.shape
    P, nkv, pg, _ = k_pages.shape
    W = page_table.shape[1]
    if nh % nkv:
        raise ValueError(f"num_heads {nh} not a multiple of kv heads {nkv}")
    rep = nh // nkv
    # GQA rep as the sublane dim of each (slot, kv-head) cell, padded to
    # the 8-sublane granule; pad rows attend real keys and are sliced off
    R8 = -(-rep // 8) * 8
    qh = q.reshape(S, nkv, rep, hd)
    if R8 != rep:
        qh = jnp.pad(qh, ((0, 0), (0, 0), (0, R8 - rep), (0, 0)))
    # the pool is STORED head-major (P, nkv, pg, hd), so KV blocks are
    # (1, 1, pg, hd) — Mosaic's last-two-dims tiling — addressed straight
    # off the table: no per-call transpose of the pool on the hot path

    grid = (S, nkv, W)
    # index maps take the grid ids plus EVERY scalar-prefetch operand
    # (2 plain, 4 with the int8 scales) — *pf absorbs the difference
    q_spec = pl.BlockSpec(
        (1, 1, R8, hd), lambda s, h, j, tbl, *pf: (s, h, 0, 0)
    )
    kv_spec = pl.BlockSpec(
        (1, 1, pg, hd), lambda s, h, j, tbl, *pf: (tbl[s, j], h, 0, 0)
    )
    prefetch = (page_table.astype(jnp.int32), kv_len.astype(jnp.int32))
    if quant:
        prefetch += (k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32))
    out = pl.pallas_call(
        functools.partial(
            _rpa_kernel, nw=W, pg=pg, sm_scale=1.0 / math.sqrt(hd),
            quant=quant,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(prefetch),
            grid=grid,
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=q_spec,
            scratch_shapes=[
                pltpu.VMEM((R8, 128), jnp.float32),
                pltpu.VMEM((R8, 128), jnp.float32),
                pltpu.VMEM((R8, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((S, nkv, R8, hd), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*prefetch, qh, k_pages, v_pages)
    return out[:, :, :rep].reshape(S, nh, hd)


# ---------------------------------------------------------------------------
# Ragged paged PREFILL attention: one chunk of prompt ingestion against
# the head-major page pool, as one kernel.
#
# The chunked hybrid prefill (models/attention.attention_mixer_chunk) used
# to scatter the chunk's K/V into pages and then GATHER the row's entire
# page view for a dense masked SDPA — O(pool width) work per chunk no
# matter how few tokens were live.  This kernel is the prefill half of
# the ragged-paged construction: grid (rows, kv-heads, page-blocks) with
# the page dimension sequential, the page table scalar-prefetched (the
# BlockSpec index map picks each row's physical page, so no (b, W*page)
# view ever exists), and every page at/past ``lengths[r] + chunk_real[r]``
# skipped outright.  The chunk's K/V page WRITE is fused in: each visited
# page merges the chunk rows that land in it (an exact one-hot-select
# matmul — every output row is one input row or the old page row) before
# the attend, and the page-pool outputs alias the inputs so XLA updates
# the pool in place.  Cells whose page takes no chunk token flush their
# (unchanged or garbage) block to the trash page via the output index
# map — a real page is only ever written by the one cell that owns it.
# ---------------------------------------------------------------------------


def _rpp_kernel(
    tbl_ref, len_ref, creal_ref, *rest,
    nw: int, pg: int, c: int, rep: int, sm_scale: float,
    quant: bool = False,
):
    """One (row, kv-head, page) cell of the fused prefill forward.

    ``quant`` (int8 page pools): four extra scalar-prefetched (P, nkv)
    f32 scale arrays — OLD and NEW for K and V.  The NEW scales are
    planned outside (models/attention._chunk_page_scales — no page
    reads needed, so nothing extra streams through the kernel); the
    kernel re-expresses the old int8 rows under the new scale
    (``round(q_old * old/new)``), quantizes the chunk's fresh rows
    BEFORE the one-hot merge, flushes the merged int8 page, and attends
    on the dequantized merged tile (scale * int8, in-register).
    """
    if quant:
        (kso_ref, ksn_ref, vso_ref, vsn_ref, q_ref, kc_ref, vc_ref,
         kp_ref, vp_ref, o_ref, ko_ref, vo_ref, m_scr, den_scr,
         acc_scr) = rest
    else:
        (q_ref, kc_ref, vc_ref, kp_ref, vp_ref, o_ref, ko_ref, vo_ref,
         m_scr, den_scr, acc_scr) = rest
    r = pl.program_id(0)
    h = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        den_scr[...] = jnp.zeros_like(den_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    ln = len_ref[r]                      # tokens cached before this chunk
    creal = creal_ref[r]                 # real (non-pad) chunk tokens
    total = ln + creal                   # readable extent after the write
    pad = c - creal                      # left-pad inside the chunk

    # ---- fused page write: merge the chunk rows landing in this page.
    # Page position t holds absolute kpos = j*pg + t and takes chunk row
    # i = kpos - ln + pad iff ln <= kpos < total; the (pg, c) one-hot
    # select contraction is exact (each output row is 1.0 * one chunk row)
    kc = kc_ref[0, 0]                                    # (C8, hd)
    vc = vc_ref[0, 0]
    C8 = kc.shape[0]
    tpos = jax.lax.broadcasted_iota(jnp.int32, (pg, C8), 0) + j * pg
    ci = jax.lax.broadcasted_iota(jnp.int32, (pg, C8), 1)
    sel = (
        (ci == tpos - ln + pad) & (tpos >= ln) & (tpos < total)
    ).astype(jnp.float32)
    k_rows = jax.lax.dot_general(                        # (pg, hd) fp32
        sel, kc.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    v_rows = jax.lax.dot_general(
        sel, vc.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    kpos_col = jax.lax.broadcasted_iota(jnp.int32, (pg, 1), 0) + j * pg
    written = (kpos_col >= ln) & (kpos_col < total)       # (pg, 1)
    if quant:
        from mamba_distributed_tpu.ops.quant import kv_quantize, kv_requant

        phys = tbl_ref[r, j]
        kso, ksn = kso_ref[phys, h], ksn_ref[phys, h]
        vso, vsn = vso_ref[phys, h], vsn_ref[phys, h]
        has_prior = ln > j * pg
        # old rows re-express under the (possibly grown) new scale; a
        # page with NO prior content of this sequence ignores its stale
        # scale outright (recycled-page garbage can't leak in).  The
        # round/clip math is the SHARED ops/quant helpers — the same
        # functions the lax fallback and the decode-step write call —
        # so the two paths can never disagree on a stored value.
        ratio_k = jnp.where(has_prior, kso / ksn, 0.0)
        ratio_v = jnp.where(has_prior, vso / vsn, 0.0)
        merged_k_q = jnp.where(
            written, kv_quantize(k_rows, ksn), kv_requant(kp_ref[0, 0],
                                                          ratio_k))
        merged_v_q = jnp.where(
            written, kv_quantize(v_rows, vsn), kv_requant(vp_ref[0, 0],
                                                          ratio_v))
        ko_ref[0, 0] = merged_k_q.astype(ko_ref.dtype)
        vo_ref[0, 0] = merged_v_q.astype(vo_ref.dtype)
        # attend on what storage now holds: dequantized requantized rows
        merged_k = merged_k_q * ksn                       # (pg, hd) fp32
        merged_v = merged_v_q * vsn
    else:
        merged_k = jnp.where(
            written, k_rows.astype(kp_ref.dtype), kp_ref[0, 0]
        )
        merged_v = jnp.where(
            written, v_rows.astype(vp_ref.dtype), vp_ref[0, 0]
        )
        # every cell writes its out block (an unwritten block would
        # flush undefined VMEM); the out index map sends no-write cells
        # to trash
        ko_ref[0, 0] = merged_k
        vo_ref[0, 0] = merged_v

    # ---- attend: whole pages at/past the row's post-write extent are
    # SKIPPED — chunk cost tracks live tokens (an all-pad row skips all)
    @pl.when(j * pg < total)
    def _():
        q = q_ref[0, 0]                                  # (Q8, hd)
        if quant:
            q = q.astype(jnp.float32)  # merged tile is dequantized fp32
        scores = jax.lax.dot_general(                    # (Q8, pg) fp32
            q, merged_k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        # sublane s is (chunk idx i = s // rep, GQA rep e = s % rep);
        # query i sits at absolute position ln + i - pad (pad queries
        # clamp to 0 — garbage that dies with its discarded positions)
        qi = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0) // rep
        qpos = jnp.maximum(ln + qi - pad, 0)
        kpos = jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1
        ) + j * pg
        mask = (kpos <= qpos) & (kpos < total)
        scores = jnp.where(mask, scores, _NEG_INF)

        # lane-replicated row stats; lane-max reads (no sub-128 slices)
        m_prev = jnp.max(m_scr[...], axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        scale = jnp.where(m_prev > _NEG_INF, jnp.exp(m_prev - m_new), 0.0)
        p = jnp.where(scores > _NEG_INF, jnp.exp(scores - m_new), 0.0)

        acc_scr[...] = acc_scr[...] * scale + jax.lax.dot_general(
            p.astype(merged_v.dtype), merged_v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        den_scr[...] = den_scr[...] * scale + jnp.sum(
            p, axis=1, keepdims=True
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(j == nw - 1)
    def _():
        den = jnp.max(den_scr[...], axis=1, keepdims=True)
        # rows with nothing readable (empty chunk on an empty cache)
        # emit zeros, not NaN
        o_ref[0, 0] = (acc_scr[...] / jnp.maximum(den, 1e-30)).astype(
            o_ref.dtype
        )


def ragged_paged_prefill_attention(
    q: jax.Array,
    k_chunk: jax.Array,
    v_chunk: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    lengths: jax.Array,
    chunk_real: jax.Array,
    k_scale_old: jax.Array | None = None,
    v_scale_old: jax.Array | None = None,
    k_scale_new: jax.Array | None = None,
    v_scale_new: jax.Array | None = None,
    interpret: bool | None = None,
):
    """Fused paged prefill: write one chunk's K/V into each row's pages,
    then attend every chunk query over the page view.

    q (b, c, nh, hd) — RoPE'd chunk queries; k_chunk/v_chunk
    (b, c, nkv, hd) — the chunk's RoPE'd K/V (left-pad prefix rows are
    ignored); k_pages/v_pages (P, nkv, pg, hd) — the shared HEAD-MAJOR
    page pool (page 0 = trash); page_table (b, W) int32; lengths (b,)
    int32 — tokens cached per row BEFORE this chunk; chunk_real (b,)
    int32 — real tokens in this chunk (c - left pad).  Real token i of
    the chunk lands at absolute position ``lengths[r] + i - pad`` and
    every query attends positions ``[0, its own position]`` — the causal
    rule over prefix + fresh chunk.

    Int8 page pools pass the four (P, nkv) f32 scale arrays — OLD and
    NEW per K/V, the NEW ones pre-planned by
    ``models/attention._chunk_page_scales`` (the caller scatters them
    into its scale arrays; this kernel only READS scales) — and the
    fused write quantizes the chunk's K/V before the one-hot merge
    while old rows requantize under the grown scale; the attend runs
    on the dequantized merged tile.

    Returns (o (b, c, nh, hd), k_pages', v_pages').  The page-pool
    outputs alias their inputs (in-place under the chunk step's state
    donation).  Numerics match the lax fallback (scatter + gather +
    ``models/attention._sdpa_positions``; int8: requant-merge +
    dequantizing gather) to fp tolerance; one jit trace covers every
    (lengths, chunk_real) mix at a fixed (b, c, W) layout
    (``TRACE_COUNTS["ragged_prefill"]``).  ``interpret=None``
    auto-selects the Pallas interpreter off-TPU.
    """
    interpret = resolve_interpret(interpret)
    TRACE_COUNTS["ragged_prefill"] += 1
    quant = k_scale_old is not None
    b, c, nh, hd = q.shape
    P, nkv, pg, _ = k_pages.shape
    W = page_table.shape[1]
    if nh % nkv:
        raise ValueError(f"num_heads {nh} not a multiple of kv heads {nkv}")
    rep = nh // nkv
    # queries head-major with (chunk idx, GQA rep) fused into the sublane
    # dim: s = i*rep + e.  Sublane pads attend real keys and are sliced
    # off; chunk-KV sublane pads are never selected by the write one-hot.
    Q = c * rep
    Q8 = -(-Q // 8) * 8
    qh = jnp.moveaxis(q.reshape(b, c, nkv, rep, hd), 1, 2)
    qh = qh.reshape(b, nkv, Q, hd)
    if Q8 != Q:
        qh = jnp.pad(qh, ((0, 0), (0, 0), (0, Q8 - Q), (0, 0)))
    C8 = -(-c // 8) * 8
    kc = jnp.moveaxis(k_chunk, 2, 1)                     # (b, nkv, c, hd)
    vc = jnp.moveaxis(v_chunk, 2, 1)
    if C8 != c:
        cpad = ((0, 0), (0, 0), (0, C8 - c), (0, 0))
        kc, vc = jnp.pad(kc, cpad), jnp.pad(vc, cpad)

    grid = (b, nkv, W)
    # index maps take the grid ids plus EVERY scalar-prefetch operand
    # (3 plain, 7 with the int8 scale arrays) — *pf absorbs the extras
    q_spec = pl.BlockSpec(
        (1, 1, Q8, hd), lambda r, h, j, tbl, *pf: (r, h, 0, 0)
    )
    c_spec = pl.BlockSpec(
        (1, 1, C8, hd), lambda r, h, j, tbl, *pf: (r, h, 0, 0)
    )
    kv_in_spec = pl.BlockSpec(
        (1, 1, pg, hd), lambda r, h, j, tbl, *pf: (tbl[r, j], h, 0, 0)
    )

    def kv_out_idx(r, h, j, tbl, ln, cr, *pf):
        # only the one cell owning a chunk-written page may flush to it;
        # everything else (pure-prefix pages, pages past the extent)
        # flushes its block to the trash page — whose content is garbage
        # by design and never read
        takes_write = (j * pg + pg > ln[r]) & (j * pg < ln[r] + cr[r])
        return (jnp.where(takes_write, tbl[r, j], 0), h, 0, 0)

    kv_out_spec = pl.BlockSpec((1, 1, pg, hd), kv_out_idx)

    prefetch = (page_table.astype(jnp.int32), lengths.astype(jnp.int32),
                chunk_real.astype(jnp.int32))
    if quant:
        prefetch += (k_scale_old.astype(jnp.float32),
                     k_scale_new.astype(jnp.float32),
                     v_scale_old.astype(jnp.float32),
                     v_scale_new.astype(jnp.float32))
    npre = len(prefetch)
    out, kp, vp = pl.pallas_call(
        functools.partial(
            _rpp_kernel, nw=W, pg=pg, c=c, rep=rep,
            sm_scale=1.0 / math.sqrt(hd), quant=quant,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=npre,
            grid=grid,
            in_specs=[q_spec, c_spec, c_spec, kv_in_spec, kv_in_spec],
            out_specs=[q_spec, kv_out_spec, kv_out_spec],
            scratch_shapes=[
                pltpu.VMEM((Q8, 128), jnp.float32),
                pltpu.VMEM((Q8, 128), jnp.float32),
                pltpu.VMEM((Q8, hd), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, nkv, Q8, hd), q.dtype),
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        # the page-pool inputs (last two operands after the scalar
        # prefetch block) alias the page-pool outputs: the write is in
        # place under donation
        input_output_aliases={npre + 3: 1, npre + 4: 2},
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*prefetch, qh, kc, vc, k_pages, v_pages)

    o = out[:, :, :Q].reshape(b, nkv, c, rep, hd)
    o = jnp.moveaxis(o, 1, 2).reshape(b, c, nh, hd)
    return o, kp, vp
