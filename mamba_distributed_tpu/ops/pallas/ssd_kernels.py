"""Pallas SSD (Mamba-2) chunked-scan kernels.

TPU-native counterpart of the Triton SSD kernels the reference depends on
(``mamba_ssm/ops/triton/ssd_chunk_scan.py`` etc., mamba-ssm 2.2.2) — but
re-derived for the MXU/VMEM model, not translated:

  * FORWARD: one fused ``pallas_call`` on grid (batch, head, chunk) with
    the chunk axis sequential — the inter-chunk state lives in VMEM
    scratch across chunk iterations (round 5; the earlier two-kernel +
    XLA state-passing pipeline doubled the call count and round-tripped
    every chunk state through HBM).  The (l x l) decay matrix ``L`` is
    rebuilt from the cumulative log-decay *inside VMEM* per cell, never
    touching HBM (the XLA path's biggest intermediate); grouped B/C are
    indexed per head via the BlockSpec index map (never repeated into
    (b, t, h, n) form);
  * BACKWARD: the entering states are recomputed (states kernel + XLA
    ``ops/ssd.state_passing`` — the remat trade), then ONE fused cell
    kernel walks the chunk axis in REVERSE (grid (batch, head, chunk),
    chunk sequential) carrying the state cotangent gP in VMEM scratch
    and emitting all per-cell input gradients plus dgamma/dinit;
  * every kernel body is strictly 2-D (l- or p-major tiles): the real
    Mosaic compiler rejects lane-splitting shape casts like
    ``(l, hb*p) -> (l, hb, p)`` at its infer-vector-layout pass — a
    failure mode ``jax.export``-based lowering tests do NOT catch (found
    on hardware, round 4) — so the head axis lives purely in the grid
    and nothing is ever reshaped in-kernel.

Training uses ``jax.custom_vjp`` with a **Pallas backward** (the analogue
of ``_mamba_chunk_scan_combined_bwd`` in the reference dep's
``mamba_ssm/ops/triton/ssd_combined.py``): activations are recomputed
chunk-locally (same remat trade the Triton path makes), the direct
state gradient and the dx/ddt/dB/dC/dA cell gradients each come from a
Pallas kernel that rebuilds the (l x l) decay matrices in VMEM, and only
the tiny inter-chunk pieces (state_passing for the recompute, the
cumsum-chain dt/A grads) stay at the XLA level.  Gradient parity vs
the XLA autodiff of ``ssd_chunked`` is pinned by tests/test_pallas.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mamba_distributed_tpu.ops.pallas.common import (
    CompilerParams,
    resolve_interpret,
)
from mamba_distributed_tpu.ops.scan import _divisor_chunk
from mamba_distributed_tpu.ops.ssd import cumsum_mxu, state_passing

# every grid cell is independent — let both megacore TensorCores split it
_PARALLEL3 = CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel"),
)


def _chunk_states_kernel(x_ref, w_ref, B_ref, out_ref, *, compute_dtype):
    """Per-chunk state contribution: out[p, n] = sum_l w*x (x) B,
    with w = dt * exp(a_last - a) precomputed in XLA."""
    w = w_ref[0, 0, 0]            # (l, 1) fp32
    Bb = B_ref[0, 0, 0]           # (l, n)
    x = x_ref[0, 0, 0]            # (l, p)

    Bd = (Bb.astype(jnp.float32) * w).astype(compute_dtype)      # (l, n)
    # x^T @ Bd: (p, l) @ (l, n) -> (p, n), contracting the sublane dim
    out_ref[0, 0, 0] = jax.lax.dot_general(
        x.astype(compute_dtype), Bd, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _cell_specs(h: int, l: int, p: int, n: int, g: int):
    """Grid-cell BlockSpecs for the backward's states-RECOMPUTE kernel
    (grid (b, nc, h), fully parallel).  The fused forward and fused
    backward build their own specs inline — their grids are (b, h, nc)
    with the chunk axis sequential (reversed index maps in the backward),
    so the index-map argument order differs; keep them in sync by hand
    when changing layouts.

    Every block spans the FULL trailing two array dims, which makes it
    unconditionally legal under Mosaic's (8, 128)-or-full-dim tiling
    rule, and every kernel-visible tile is 2-D — the head axis lives in
    the grid, never inside a block (layouts built by _chunked_inputs):
      x       (b, nc, h, l, p)       one head per cell
      w       (b, nc, h, l, 1)       lane-degenerate per-head columns
      B       (b, nc, g, l, n)       cell's group via the index map
      states  (b, nc, h, p, n)       (p, n) trailing dims; p % 8 asserted
    """
    xhp_spec = pl.BlockSpec(
        (1, 1, 1, l, p), lambda bi, ci, hi: (bi, ci, hi, 0, 0)
    )
    dt_spec = pl.BlockSpec(
        (1, 1, 1, l, 1), lambda bi, ci, hi: (bi, ci, hi, 0, 0)
    )
    bc_spec = pl.BlockSpec(
        (1, 1, 1, l, n), lambda bi, ci, hi: (bi, ci, (hi * g) // h, 0, 0)
    )
    st_spec = pl.BlockSpec(
        (1, 1, 1, p, n), lambda bi, ci, hi: (bi, ci, hi, 0, 0)
    )
    return xhp_spec, dt_spec, bc_spec, st_spec


def _to_cells(v, b, nc, l, h, tail):
    """(b, t, h, *tail) -> (b, nc, h, l, prod(tail) or 1)."""
    v = v.reshape(b, nc, l, h, *tail)
    v = jnp.moveaxis(v, 3, 2)                        # (b, nc, h, l, ...)
    return v.reshape(b, nc, h, l, -1)


def _from_cells(v, b, t, h, p):
    """(b, nc, h, l, p) -> (b, t, h, p)."""
    nc, l = v.shape[1], v.shape[3]
    v = jnp.moveaxis(v, 2, 3)                        # (b, nc, l, h, p)
    return v.reshape(b, t, h, p)


def _chunked_inputs(x, dt, A, B, C, chunk_size):
    """Shared fwd/bwd preprocessing: chunk/cell layouts + in-chunk log-decay.

    All the elementwise decay factors the kernels need are precomputed
    here (they fuse into the cumsum chain): ``ar``/``art`` are the
    cumulative log-decay in column (l, 1) / row (1, l) cell layouts,
    ``er`` = exp(a), ``wr`` = dt * exp(a_last - a), ``dr`` =
    exp(a_last - a).  Everything is bounded by exp(0) = 1 (a is a cumsum
    of dt*A <= 0), so none of the exps can overflow.
    """
    b, t, h, p = x.shape
    g, n = B.shape[2], B.shape[3]
    l = _divisor_chunk(t, chunk_size)
    nc = t // l
    if p % 8 != 0:  # the (p, n)-trailing state blocks need 8-sublane tiles
        raise ValueError(
            f"ssm_impl='pallas' needs headdim % 8 == 0 for Mosaic tiling, "
            f"got headdim={p}; use ssm_impl='xla' for this shape"
        )

    dtf = dt.astype(jnp.float32)
    dA = dtf * A.astype(jnp.float32)                 # (b, t, h)
    a_cum = cumsum_mxu(dA.reshape(b, nc, l, h), axis=2)          # (b, nc, l, h)
    chunk_decay = jnp.exp(a_cum[:, :, -1, :])        # (b, nc, h)
    d_to_end = jnp.exp(a_cum[:, :, -1:, :] - a_cum)  # (b, nc, l, h)

    flat = lambda v: v.reshape(b, t, h)
    xr = _to_cells(x, b, nc, l, h, (p,))
    dtr = _to_cells(dtf, b, nc, l, h, ())
    ar = _to_cells(flat(a_cum), b, nc, l, h, ())
    er = _to_cells(flat(jnp.exp(a_cum)), b, nc, l, h, ())
    dr = _to_cells(flat(d_to_end), b, nc, l, h, ())
    art = jnp.swapaxes(ar, 3, 4)                     # (b, nc, h, 1, l)
    Br = jnp.moveaxis(B.reshape(b, nc, l, g, n), 3, 2)           # (b, nc, g, l, n)
    Cr = jnp.moveaxis(C.reshape(b, nc, l, g, n), 3, 2)
    cells = {
        "x": xr, "dt": dtr, "a": ar, "at": art, "e": er, "d": dr,
        "w": dtr * dr, "B": Br, "C": Cr,
    }
    return cells, chunk_decay, (b, nc, l, h, p, g, n)


def _ssd_fused_fwd_kernel(
    x_ref, dt_ref, ac_ref, at_ref, e_ref, w_ref, g_ref, B_ref, C_ref,
    h0_ref, y_ref, hT_ref, state, *, compute_dtype, nc,
):
    """ONE cell = (batch, head, chunk) with the chunk axis SEQUENTIAL:
    the inter-chunk state lives in VMEM scratch across chunk iterations,
    so the per-chunk states never round-trip HBM and the whole forward is
    a single pallas_call (round-5 fusion: the two-kernel + XLA
    state-passing pipeline cost ~2x the calls and ~100 MB/layer of state
    traffic; same math, same strictly-2-D bodies).
    """
    ci = pl.program_id(2)

    @pl.when(ci == 0)
    def _init():
        state[...] = h0_ref[0, 0]                    # (p, n) fp32

    ac = ac_ref[0, 0, 0]                             # (l, 1) fp32
    at = at_ref[0, 0, 0]                             # (1, l) fp32
    dt = dt_ref[0, 0, 0]                             # (l, 1)
    e = e_ref[0, 0, 0]                               # (l, 1) = exp(a)
    w = w_ref[0, 0, 0]                               # (l, 1) = dt*exp(aL-a)
    Bb = B_ref[0, 0, 0]                              # (l, n)
    Cb = C_ref[0, 0, 0]                              # (l, n)
    l = ac.shape[0]
    x = x_ref[0, 0, 0]                               # (l, p)
    prev = state[...]                                # (p, n) fp32

    # --- intra-chunk output: (G .* L) @ (x*dt)  [NT dots, no transposes]
    G = jax.lax.dot_general(
        Cb.astype(compute_dtype), Bb.astype(compute_dtype),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )                                                # (l, l)
    ii = jax.lax.broadcasted_iota(jnp.int32, (l, l), 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, (l, l), 1)
    M = jnp.where(ii >= jj, G * jnp.exp(ac - at), 0.0)
    xdt = (x.astype(jnp.float32) * dt).astype(compute_dtype)
    y = jnp.dot(M.astype(compute_dtype), xdt,
                preferred_element_type=jnp.float32)  # (l, p)

    # --- carried-state contribution: (C*e^a) @ prev^T
    cd = (Cb.astype(jnp.float32) * e).astype(compute_dtype)
    y = y + jax.lax.dot_general(
        cd, prev.astype(compute_dtype), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    y_ref[0, 0, 0] = y.astype(y_ref.dtype)

    # --- state update: new = exp(a_last)*prev + x^T @ (w*B)
    Bd = (Bb.astype(jnp.float32) * w).astype(compute_dtype)      # (l, n)
    S = jax.lax.dot_general(                         # (p, n)
        x.astype(compute_dtype), Bd, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    gamma = g_ref[0, 0, 0]                           # (1, 1) chunk decay
    state[...] = gamma * prev + S

    @pl.when(ci == nc - 1)
    def _emit_final():
        hT_ref[0, 0] = state[...]


def _ssd_pallas_fwd_impl(
    x, dt, A, B, C, chunk_size, initial_state, compute_dtype, interpret
):
    """Forward via ONE fused kernel (sequential chunk axis, VMEM state).

    Shapes: x (b,t,h,p); dt (b,t,h) [bias-added+softplused]; A (h,);
    B/C (b,t,g,n).  Returns (y_no_D (b,t,h,p) fp32-accurate, final_state).
    """
    cells, chunk_decay, dims = _chunked_inputs(x, dt, A, B, C, chunk_size)
    b, nc, l, h, p, g, n = dims
    t = nc * l

    h0 = (jnp.zeros((b, h, p, n), jnp.float32) if initial_state is None
          else initial_state.astype(jnp.float32))
    # chunk decay exp(a_last) as (b, nc, h, 1, 1) cells — a (1, 1) block
    # read beats an in-kernel last-row scalar index under Mosaic
    gamma_cells = chunk_decay[:, :, :, None, None]

    # grid (b, h, nc): chunk axis LAST and sequential so the scratch state
    # carries; b x h cells stay parallel for the megacore split
    def cell5(last_two):
        return pl.BlockSpec((1, 1, 1) + last_two,
                            lambda bi, hi, ci: (bi, ci, hi, 0, 0))

    bc5 = pl.BlockSpec((1, 1, 1, l, n),
                       lambda bi, hi, ci: (bi, ci, (hi * g) // h, 0, 0))
    h_spec = pl.BlockSpec((1, 1, p, n), lambda bi, hi, ci: (bi, hi, 0, 0))

    y, final_state = pl.pallas_call(
        functools.partial(_ssd_fused_fwd_kernel,
                          compute_dtype=compute_dtype, nc=nc),
        out_shape=(
            jax.ShapeDtypeStruct((b, nc, h, l, p), x.dtype),
            jax.ShapeDtypeStruct((b, h, p, n), jnp.float32),
        ),
        grid=(b, h, nc),
        in_specs=[cell5((l, p)), cell5((l, 1)), cell5((l, 1)),
                  cell5((1, l)), cell5((l, 1)), cell5((l, 1)),
                  cell5((1, 1)), bc5, bc5, h_spec],
        out_specs=(cell5((l, p)), h_spec),
        scratch_shapes=[pltpu.VMEM((p, n), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(cells["x"], cells["dt"], cells["a"], cells["at"], cells["e"],
      cells["w"], gamma_cells, cells["B"], cells["C"], h0)

    return _from_cells(y, b, t, h, p), final_state


# ---------------------------------------------------------------------------
# Backward pass (training path): Pallas kernels + tiny XLA glue.
#
# Forward decomposition per chunk (head h, in-chunk log-decay a = cumsum(dt*A)):
#   y_diag = (G .* L) @ (dt*x)      G[i,j] = <C_i, B_j>, L[i,j] = e^{a_i-a_j}
#   S      = sum_j e^{a_L-a_j} dt_j x_j (x) B_j     (per-chunk state summary)
#   P_{c+1} = gamma_c P_c + S_c,  gamma_c = e^{a_L}  (inter-chunk recurrence)
#   y_off  = diag(e^a) C @ P_c^T
# The backward mirrors it: (1) the forward's states kernel + XLA
# state_passing recompute the entering states P_c (remat, same trade as
# the Triton backward); (2) ONE fused cell kernel walks the chunk axis in
# REVERSE (index maps ci -> nc-1-ci, sequential grid dim) carrying the
# state cotangent gP in VMEM scratch — gP_c = dP_c + gamma_c gP_{c+1}
# with dP_c = dY^T (e^a .* C) computed in-cell, dS_c = gP_{c+1} consumed
# before the update, and dgamma_c = <dS_c, P_c> emitted per cell (the
# round-4 design ran a separate dP kernel plus an XLA reverse
# associative_scan, round-tripping two (b, nc, h, p, n) arrays through
# HBM); (3) an XLA epilogue pushes the in-chunk log-decay gradient `da`
# through the cumsum chain into ddt and dA.
# ---------------------------------------------------------------------------


def _ssd_fused_bwd_kernel(
    x_ref, dt_ref, ac_ref, at_ref, e_ref, d_ref, g_ref, B_ref, C_ref,
    prev_ref, dy_ref, dfin_ref,
    dx_ref, ddt_ref, da_ref, dB_ref, dC_ref, dg_ref, dinit_ref,
    gP, *, compute_dtype, nc,
):
    """All per-cell input gradients for one (batch, head, chunk-reversed).

    Strictly 2-D bodies (see module docstring): sublane-axis sums go
    through ones-vector matmuls instead of transposes, and all decay
    factors (e = exp(a), d = exp(a_last - a), gamma = exp(a_last),
    row/col a) arrive precomputed from XLA.

    Outputs: dx (l,p); ddt_direct (l,1) [the dt*x product-rule term];
    da (l,1) [grad wrt the in-chunk cumulative log-decay, pushed through
    the cumsum chain by the XLA epilogue]; dB/dC (l,n) per head
    [summed over a group's heads outside]; dgamma (1,1); dinit (p,n)
    [the state cotangent after chunk 0, emitted on the last iteration].
    """
    cd = compute_dtype
    ci = pl.program_id(2)

    @pl.when(ci == 0)                                # actual chunk nc-1
    def _seed():
        gP[...] = dfin_ref[0, 0]                     # dfinal or zeros

    ac = ac_ref[0, 0, 0]                             # (l, 1) fp32
    at = at_ref[0, 0, 0]                             # (1, l) fp32
    dt = dt_ref[0, 0, 0]                             # (l, 1) fp32
    e = e_ref[0, 0, 0]                               # (l, 1) = exp(a)
    d = d_ref[0, 0, 0]                               # (l, 1) decay-to-end
    gamma = g_ref[0, 0, 0]                           # (1, 1) = exp(a_last)
    l = ac.shape[0]
    x = x_ref[0, 0, 0].astype(jnp.float32)           # (l, p)
    Bb = B_ref[0, 0, 0]                              # (l, n)
    Cb = C_ref[0, 0, 0]                              # (l, n)
    P = prev_ref[0, 0, 0]                            # (p, n) fp32
    dy = dy_ref[0, 0, 0].astype(jnp.float32)         # (l, p)
    dS = gP[...]                                     # = gP_{c+1} (p, n)
    ones = jnp.ones((l, 1), jnp.float32)

    u = x * dt                                       # (l, p)

    # --- intra-chunk: y_diag = (G .* L) @ u -------------------------------
    G = jax.lax.dot_general(                         # (l, l), NT form
        Cb.astype(cd), Bb.astype(cd), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ii = jax.lax.broadcasted_iota(jnp.int32, (l, l), 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, (l, l), 1)
    tril = ii >= jj
    Lm = jnp.where(tril, jnp.exp(ac - at), 0.0)      # (l, l)
    M = G * Lm                                       # (l, l) fp32

    dM = jax.lax.dot_general(                        # dM = dY @ u^T  (l, l)
        dy.astype(cd), u.astype(cd), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    du = jax.lax.dot_general(                        # du = M^T @ dY  (l, p)
        M.astype(cd), dy.astype(cd), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    dMM = dM * M                                     # = dL .* L .* G
    rowsum = jnp.sum(dMM, axis=1, keepdims=True)     # (l, 1) lane reduction
    colsum = jax.lax.dot_general(                    # dMM^T @ 1 -> (l, 1)
        dMM, ones, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    da = rowsum - colsum                             # (l, 1)
    dG = dM * Lm                                     # (l, l), masked by Lm
    dB_acc = jax.lax.dot_general(                    # dG^T @ C  (l, n)
        dG.astype(cd), Cb.astype(cd), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dC_acc = jnp.dot(dG.astype(cd), Bb.astype(cd),
                     preferred_element_type=jnp.float32)         # (l, n)

    # --- off-diagonal: y_off = diag(e) C @ P^T ----------------------------
    T = jnp.dot(dy.astype(cd), P.astype(cd),
                preferred_element_type=jnp.float32)  # (l, n) = dY @ P
    dC_acc = dC_acc + e * T
    de = jnp.sum(T * Cb.astype(jnp.float32), axis=1, keepdims=True)  # (l, 1)
    da = da + de * e

    # --- state summary: S = sum_j d_j u_j (x) B_j -------------------------
    dw = jax.lax.dot_general(                        # B @ dS^T  (l, p)
        Bb.astype(cd), dS.astype(cd), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    w = u * d                                        # (l, p)
    dB_acc = dB_acc + jax.lax.dot_general(           # w^T-free NT: w @ dS
        w.astype(cd), dS.astype(cd), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                # (l, n)
    du = du + d * dw
    dd = jnp.sum(u * dw, axis=1, keepdims=True)      # (l, 1)
    ddd = dd * d                                     # chain through exp
    da = da - ddd
    # += at the last row, as a mask-add (scatter has no Mosaic lowering);
    # the total over l comes from a ones-matmul (no sublane transpose)
    total = jax.lax.dot_general(                     # (1, 1)
        ones, ddd, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    last = (jax.lax.broadcasted_iota(jnp.int32, (l, 1), 0) == l - 1)
    da = da + jnp.where(last, total, 0.0)

    # --- u = dt * x product rule ------------------------------------------
    dx_ref[0, 0, 0] = (dt * du).astype(dx_ref.dtype)
    ddt_ref[0, 0, 0] = jnp.sum(x * du, axis=1, keepdims=True)
    da_ref[0, 0, 0] = da
    dB_ref[0, 0, 0] = dB_acc
    dC_ref[0, 0, 0] = dC_acc

    # --- inter-chunk recurrence cotangents --------------------------------
    # dgamma_c = <dS_c, P_c>: lane-reduce then a ones-matmul over sublanes
    sp = jnp.sum(dS * P, axis=1, keepdims=True)      # (p, 1)
    dg_ref[0, 0, 0] = jax.lax.dot_general(           # (1, 1)
        jnp.ones((P.shape[0], 1), jnp.float32), sp,
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    # gP_c = dP_c + gamma_c * gP_{c+1},  dP_c = dY^T @ (e^a .* C)
    eC = (e * Cb.astype(jnp.float32)).astype(cd)     # (l, n)
    dP = jax.lax.dot_general(                        # (p, n)
        dy.astype(cd), eC, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    gP[...] = dP + gamma * dS

    @pl.when(ci == nc - 1)                           # actual chunk 0
    def _emit_dinit():
        dinit_ref[0, 0] = gP[...]


def _ssd_pallas_bwd_impl(
    x, dt, A, B, C, dy, chunk_size, compute_dtype, interpret,
    initial_state=None, dfinal=None,
):
    """Full backward: recompute chunk states, then ONE fused reverse-walk
    cell kernel (state cotangent carried in VMEM scratch).

    ``initial_state`` (b, h, p, n) makes the recomputed entering states
    match a forward that was seeded (decode prefill / SP shards), and its
    gradient is returned as the sixth output.  ``dfinal`` is the cotangent
    of the final state when the forward returned it; it seeds the reverse
    state scan the same way ``initial_state`` seeds the forward one.
    """
    cells, chunk_decay, dims = _chunked_inputs(x, dt, A, B, C, chunk_size)
    b, nc, l, h, p, g, n = dims
    t = nc * l
    grid = (b, nc, h)
    xhp_spec, dt_spec, bc_spec, st_spec = _cell_specs(h, l, p, n, g)
    dyr = _to_cells(dy, b, nc, l, h, (p,))

    # recompute the chunk summaries + entering states (remat, like the
    # reference dep's Triton backward which re-derives chunk states)
    states = pl.pallas_call(
        functools.partial(_chunk_states_kernel, compute_dtype=compute_dtype),
        out_shape=jax.ShapeDtypeStruct((b, nc, h, p, n), jnp.float32),
        grid=grid,
        in_specs=[xhp_spec, dt_spec, bc_spec],
        out_specs=st_spec,
        compiler_params=_PARALLEL3,
        interpret=interpret,
    )(cells["x"], cells["w"], cells["B"])
    prev_states, _ = state_passing(states, chunk_decay, initial_state)

    # ONE fused kernel walks the chunk axis in reverse (sequential grid
    # dim, index maps ci -> nc-1-ci) carrying the state cotangent gP in
    # VMEM scratch; a final-state cotangent seeds gP exactly like the old
    # virtual-chunk trick seeded the associative scan
    dfin = (jnp.zeros((b, h, p, n), jnp.float32) if dfinal is None
            else dfinal.astype(jnp.float32))
    gamma_cells = chunk_decay[:, :, :, None, None]   # (b, nc, h, 1, 1)

    def cell5r(last_two):
        return pl.BlockSpec(
            (1, 1, 1) + last_two,
            lambda bi, hi, ci: (bi, nc - 1 - ci, hi, 0, 0),
        )

    bc5r = pl.BlockSpec(
        (1, 1, 1, l, n),
        lambda bi, hi, ci: (bi, nc - 1 - ci, (hi * g) // h, 0, 0),
    )
    st5r = pl.BlockSpec(
        (1, 1, 1, p, n), lambda bi, hi, ci: (bi, nc - 1 - ci, hi, 0, 0)
    )
    h_spec = pl.BlockSpec((1, 1, p, n), lambda bi, hi, ci: (bi, hi, 0, 0))

    dx_c, ddt5, da5, dB_cell, dC_cell, dg5, dinit_arr = pl.pallas_call(
        functools.partial(_ssd_fused_bwd_kernel,
                          compute_dtype=compute_dtype, nc=nc),
        out_shape=(
            jax.ShapeDtypeStruct((b, nc, h, l, p), x.dtype),
            jax.ShapeDtypeStruct((b, nc, h, l, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, nc, h, l, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, nc, h, l, n), jnp.float32),
            jax.ShapeDtypeStruct((b, nc, h, l, n), jnp.float32),
            jax.ShapeDtypeStruct((b, nc, h, 1, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, h, p, n), jnp.float32),
        ),
        grid=(b, h, nc),
        in_specs=[cell5r((l, p)), cell5r((l, 1)), cell5r((l, 1)),
                  cell5r((1, l)), cell5r((l, 1)), cell5r((l, 1)),
                  cell5r((1, 1)), bc5r, bc5r, st5r, cell5r((l, p)), h_spec],
        out_specs=(cell5r((l, p)), cell5r((l, 1)), cell5r((l, 1)),
                   cell5r((l, n)), cell5r((l, n)), cell5r((1, 1)), h_spec),
        scratch_shapes=[pltpu.VMEM((p, n), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(cells["x"], cells["dt"], cells["a"], cells["at"], cells["e"],
      cells["d"], gamma_cells, cells["B"], cells["C"], prev_states, dyr,
      dfin)

    # gradient wrt the state entering chunk 0 == wrt initial_state
    dinit = dinit_arr if initial_state is not None else None
    dgamma = dg5[..., 0, 0]                          # (b, nc, h)

    # --- XLA epilogue: push `da` through the cumsum chain -----------------
    def cells_to_blh(v):  # (b, nc, h, l, 1) -> (b, nc, l, h)
        return jnp.moveaxis(v, 2, 3).reshape(b, nc, l, h)

    da = cells_to_blh(da5)
    ddt_dir = cells_to_blh(ddt5)
    da = da.at[:, :, -1, :].add(dgamma * chunk_decay)
    ddA = cumsum_mxu(da, axis=2, reverse=True)                   # (b, nc, l, h)
    Af = A.astype(jnp.float32)
    ddt = (ddt_dir + ddA * Af[None, None, None]).reshape(b, t, h)
    dA = jnp.sum(ddA * cells_to_blh(cells["dt"]), axis=(0, 1, 2))

    # group-sum the per-head B/C gradients (cells are head-ordered,
    # so a group's h/g heads are consecutive)
    dB_g = dB_cell.reshape(b, nc, g, h // g, l, n).sum(axis=3)
    dC_g = dC_cell.reshape(b, nc, g, h // g, l, n).sum(axis=3)
    dB = jnp.transpose(dB_g, (0, 1, 3, 2, 4)).reshape(b, t, g, n)
    dC = jnp.transpose(dC_g, (0, 1, 3, 2, 4)).reshape(b, t, g, n)

    return (
        _from_cells(dx_c, b, t, h, p),
        ddt.astype(dt.dtype),
        dA.astype(A.dtype),
        dB.astype(B.dtype),
        dC.astype(C.dtype),
        dinit,
    )


def _add_D(y, x, D):
    if D is None:
        return y
    Df = D.astype(jnp.float32)
    yf = y.astype(jnp.float32) + x.astype(jnp.float32) * (
        Df[None, None, :, :] if Df.ndim == 2 else Df[None, None, :, None]
    )
    return yf.astype(x.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9)
)
def _ssd_pallas_core(
    x, dt, A, B, C, initial_state, chunk_size, compute_dtype, interpret,
    return_final_state,
):
    y, final = _ssd_pallas_fwd_impl(
        x, dt, A, B, C, chunk_size, initial_state, compute_dtype, interpret
    )
    return (y, final) if return_final_state else y


def _core_fwd(
    x, dt, A, B, C, initial_state, chunk_size, compute_dtype, interpret,
    return_final_state,
):
    out = _ssd_pallas_core(
        x, dt, A, B, C, initial_state, chunk_size, compute_dtype, interpret,
        return_final_state,
    )
    return out, (x, dt, A, B, C, initial_state)


def _core_bwd(chunk_size, compute_dtype, interpret, return_final_state, res, ct):
    """Pallas backward (see the backward section above)."""
    x, dt, A, B, C, initial_state = res
    dy, dfinal = ct if return_final_state else (ct, None)
    dx, ddt, dA, dB, dC, dinit = _ssd_pallas_bwd_impl(
        x, dt, A, B, C, dy, chunk_size, compute_dtype, interpret,
        initial_state=initial_state, dfinal=dfinal,
    )
    return dx, ddt, dA, dB, dC, dinit


_ssd_pallas_core.defvjp(_core_fwd, _core_bwd)


def ssd_chunked_pallas(
    x: jax.Array,
    dt: jax.Array,
    A: jax.Array,
    B: jax.Array,
    C: jax.Array,
    chunk_size: int = 256,
    D: jax.Array | None = None,
    initial_state: jax.Array | None = None,
    return_final_state: bool = False,
    compute_dtype=jnp.bfloat16,
    interpret: bool | None = None,
):
    """Drop-in for ops/ssd.ssd_chunked backed by Pallas kernels.

    Every path — plain training, seeded (``initial_state``: decode
    prefill / SP shards), and ``return_final_state`` — runs under the
    custom VJP whose backward is itself Pallas (kernels above): the
    seeded forward recomputes entering states from the same seed, a
    final-state cotangent seeds the reverse state scan, and the
    initial-state gradient comes back as ``gP[0]``.  ``interpret=None``
    auto-selects the Pallas interpreter off-TPU (CPU tests run the same
    kernel code).
    """
    interpret = resolve_interpret(interpret)
    out = _ssd_pallas_core(
        x, dt, A, B, C, initial_state, chunk_size, compute_dtype, interpret,
        return_final_state,
    )
    if return_final_state:
        y, final_state = out
        return _add_D(y, x, D), final_state
    return _add_D(out, x, D)
