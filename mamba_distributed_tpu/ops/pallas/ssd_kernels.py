"""Pallas SSD (Mamba-2) chunked-scan kernels.

TPU-native counterpart of the Triton SSD kernels the reference depends on
(``mamba_ssm/ops/triton/ssd_chunk_scan.py`` etc., mamba-ssm 2.2.2) — but
re-derived for the MXU/VMEM model, not translated:

  * one grid cell = (batch, chunk, head-block); the (l x l) decay matrix
    ``L`` is rebuilt from the cumulative log-decay *inside VMEM* per cell,
    never touching HBM (the XLA path's biggest intermediate);
  * the two sequential pieces stay at the XLA level where they belong:
    the inter-chunk state recurrence is a tiny ``associative_scan``
    (ops/ssd.state_passing), and grouped B/C are indexed per head-block
    via the BlockSpec index map (never repeated into (b, t, h, n) form);
  * heads are processed ``hb = 128 // headdim`` at a time so the lane
    dimension of the y/x tiles stays full.

Training uses ``jax.custom_vjp`` with a **Pallas backward** (the analogue
of ``_mamba_chunk_scan_combined_bwd`` in the reference dep's
``mamba_ssm/ops/triton/ssd_combined.py``): activations are recomputed
chunk-locally (same remat trade the Triton path makes), the direct
state gradient and the dx/ddt/dB/dC/dA cell gradients each come from a
Pallas kernel that rebuilds the (l x l) decay matrices in VMEM, and only
the tiny inter-chunk pieces (reverse associative scan over chunk states,
the cumsum-chain dt/A grads) stay at the XLA level.  Gradient parity vs
the XLA autodiff of ``ssd_chunked`` is pinned by tests/test_pallas.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mamba_distributed_tpu.ops.pallas.common import resolve_interpret
from mamba_distributed_tpu.ops.scan import _divisor_chunk
from mamba_distributed_tpu.ops.ssd import state_passing

# every grid cell is independent — let both megacore TensorCores split it
_PARALLEL3 = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel"),
)


def _chunk_states_kernel(x_ref, dt_ref, acum_ref, B_ref, out_ref, *, compute_dtype):
    """Per-chunk state contribution: out[hb, p, n] = sum_l decay*dt*x (x) B."""
    a = acum_ref[0, 0, 0]         # (l, hb) fp32, inclusive cumsum of dt*A
    dt = dt_ref[0, 0, 0]          # (l, hb) fp32
    Bb = B_ref[0, 0, 0]           # (l, n)
    l, hb = a.shape
    x = x_ref[0, 0, 0].reshape(l, hb, -1)   # (l, hb, p)

    decay = jnp.exp(a[-1:, :] - a) * dt            # (l, hb)
    Bd = Bb[:, None, :] * decay[:, :, None]        # (l, hb, n)
    # batched over hb: (hb, p, l) @ (hb, l, n) -> (hb, p, n)
    xt = jnp.transpose(x, (1, 2, 0)).astype(compute_dtype)
    Bt = jnp.transpose(Bd, (1, 0, 2)).astype(compute_dtype)
    out_ref[0, 0] = jax.lax.dot_general(
        xt, Bt, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )


def _chunk_output_kernel(
    x_ref, dt_ref, acum_ref, B_ref, C_ref, prev_ref, y_ref, *, compute_dtype
):
    """y = (G odot L) @ (x*dt) + (C*exp(a)) @ prev_state^T for one cell."""
    a = acum_ref[0, 0, 0]         # (l, hb) fp32
    dt = dt_ref[0, 0, 0]          # (l, hb)
    Bb = B_ref[0, 0, 0].astype(compute_dtype)      # (l, n)
    Cb = C_ref[0, 0, 0].astype(compute_dtype)      # (l, n)
    l, hb = a.shape
    x = x_ref[0, 0, 0].reshape(l, hb, -1)          # (l, hb, p)
    prev = prev_ref[0, 0]         # (hb, p, n) fp32

    # G is group-shared across the hb heads of this block
    G = jnp.dot(Cb, Bb.T, preferred_element_type=jnp.float32)  # (l, l)

    # decay matrix rebuilt in VMEM: L[h, i, j] = exp(a_i - a_j) on i >= j
    ai = a.T[:, :, None]          # (hb, l, 1)
    aj = a.T[:, None, :]          # (hb, 1, l)
    ii = jax.lax.broadcasted_iota(jnp.int32, (l, l), 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, (l, l), 1)
    tril = ii >= jj
    M = jnp.where(tril[None], G[None] * jnp.exp(ai - aj), 0.0)  # (hb, l, l)

    xdt = (x.astype(jnp.float32) * dt[:, :, None]).astype(compute_dtype)
    xdt_t = jnp.transpose(xdt, (1, 0, 2))          # (hb, l, p)
    y = jax.lax.dot_general(
        M.astype(compute_dtype), xdt_t, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                              # (hb, l, p)

    # off-diagonal: carried-state contribution
    cd = (Cb[None] * jnp.exp(a.T)[:, :, None]).astype(compute_dtype)  # (hb, l, n)
    y = y + jax.lax.dot_general(
        cd, jnp.transpose(prev, (0, 2, 1)).astype(compute_dtype),
        (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    y_ref[0, 0, 0] = (
        jnp.transpose(y, (1, 0, 2)).reshape(l, -1).astype(y_ref.dtype)
    )  # (l, hb*p)


def _heads_per_block(h: int, p: int, g: int, max_hb: int | None = None) -> int:
    hb = max(1, 128 // p)
    if max_hb is not None:
        hb = max(1, min(hb, max_hb))
    heads_per_group = h // g
    while heads_per_group % hb != 0 or h % hb != 0:
        hb -= 1
    return max(hb, 1)


def _bwd_hb_cap(l: int) -> int:
    """VMEM guard for the backward cell kernel (ADVICE r3): it holds ~5
    (hb, l, l) fp32 tensors live (diff, Lm, M, dM, dMM), so cap hb to
    keep that working set under ~4MB — the same budget the m1 backward's
    rebuilt-state scratch honors.  Small headdim + large chunk (p=8 ->
    hb=16 at l=256 would be ~20MB) is exactly the case this catches."""
    budget = 4 * 1024 * 1024
    return max(1, budget // (5 * l * l * 4))


def _cell_specs(h: int, hb: int, l: int, p: int, n: int, g: int):
    """Grid-cell BlockSpecs shared by the fwd and bwd kernels.

    Every block spans the FULL trailing two array dims, which makes it
    unconditionally legal under Mosaic's (8, 128)-or-full-dim tiling
    rule — the head-block structure lives in a dedicated array axis
    instead of a partial-dim block (layouts built by _chunked_inputs):
      x/y/dy  (b, nc, nhb, l, hb*p)   one lane-filling head-block per cell
      dt/a    (b, nc, nhb, l, hb)
      B/C     (b, nc, g,   l, n)      cell's group via the index map
      states  (b, nc, h, p, n)        (p, n) trailing dims; p % 8 asserted
    """
    xhp_spec = pl.BlockSpec(
        (1, 1, 1, l, hb * p), lambda bi, ci, hi: (bi, ci, hi, 0, 0)
    )
    dt_spec = pl.BlockSpec(
        (1, 1, 1, l, hb), lambda bi, ci, hi: (bi, ci, hi, 0, 0)
    )
    bc_spec = pl.BlockSpec(
        (1, 1, 1, l, n), lambda bi, ci, hi: (bi, ci, (hi * hb * g) // h, 0, 0)
    )
    st_spec = pl.BlockSpec((1, 1, hb, p, n), lambda bi, ci, hi: (bi, ci, hi, 0, 0))
    return xhp_spec, dt_spec, bc_spec, st_spec


def _to_cells(v, b, nc, l, nhb, hb, tail):
    """(b, t, h, *tail) -> (b, nc, nhb, l, hb*prod(tail))."""
    v = v.reshape(b, nc, l, nhb, hb, *tail)
    v = jnp.moveaxis(v, 3, 2)                        # (b, nc, nhb, l, hb, ...)
    return v.reshape(b, nc, nhb, l, -1)


def _from_cells(v, b, t, h, p):
    """(b, nc, nhb, l, hb*p) -> (b, t, h, p)."""
    nc, nhb = v.shape[1], v.shape[2]
    l = v.shape[3]
    hb = h // nhb
    v = v.reshape(b, nc, nhb, l, hb, p)
    v = jnp.moveaxis(v, 2, 3)                        # (b, nc, l, nhb, hb, p)
    return v.reshape(b, t, h, p)


def _chunked_inputs(x, dt, A, B, C, chunk_size, max_hb=None):
    """Shared fwd/bwd preprocessing: chunk/cell layouts + in-chunk log-decay."""
    b, t, h, p = x.shape
    g, n = B.shape[2], B.shape[3]
    l = _divisor_chunk(t, chunk_size)
    nc = t // l
    hb = _heads_per_block(h, p, g, max_hb)
    nhb = h // hb
    if p % 8 != 0:  # the (p, n)-trailing state blocks need 8-sublane tiles
        raise ValueError(
            f"ssm_impl='pallas' needs headdim % 8 == 0 for Mosaic tiling, "
            f"got headdim={p}; use ssm_impl='xla' for this shape"
        )

    dtf = dt.astype(jnp.float32)
    dA = dtf * A.astype(jnp.float32)                 # (b, t, h)
    a_cum = jnp.cumsum(dA.reshape(b, nc, l, h), axis=2)          # (b, nc, l, h)
    chunk_decay = jnp.exp(a_cum[:, :, -1, :])        # (b, nc, h)

    xr = _to_cells(x, b, nc, l, nhb, hb, (p,))
    dtr = _to_cells(dtf, b, nc, l, nhb, hb, ())
    ar = _to_cells(a_cum.reshape(b, t, h), b, nc, l, nhb, hb, ())
    Br = jnp.moveaxis(B.reshape(b, nc, l, g, n), 3, 2)           # (b, nc, g, l, n)
    Cr = jnp.moveaxis(C.reshape(b, nc, l, g, n), 3, 2)
    return xr, dtr, ar, chunk_decay, Br, Cr, (b, nc, l, h, hb, p, g, n)


def _ssd_pallas_fwd_impl(
    x, dt, A, B, C, chunk_size, initial_state, compute_dtype, interpret
):
    """Forward via the two kernels + XLA state passing.

    Shapes: x (b,t,h,p); dt (b,t,h) [bias-added+softplused]; A (h,);
    B/C (b,t,g,n).  Returns (y_no_D (b,t,h,p) fp32-accurate, final_state).
    """
    xr, dtr, ar, chunk_decay, Br, Cr, dims = _chunked_inputs(
        x, dt, A, B, C, chunk_size
    )
    b, nc, l, h, hb, p, g, n = dims
    t = nc * l
    nhb = h // hb

    grid = (b, nc, nhb)
    xhp_spec, dt_spec, bc_spec, st_spec = _cell_specs(h, hb, l, p, n, g)

    states = pl.pallas_call(
        functools.partial(_chunk_states_kernel, compute_dtype=compute_dtype),
        out_shape=jax.ShapeDtypeStruct((b, nc, h, p, n), jnp.float32),
        grid=grid,
        in_specs=[xhp_spec, dt_spec, dt_spec, bc_spec],
        out_specs=st_spec,
        compiler_params=_PARALLEL3,
        interpret=interpret,
    )(xr, dtr, ar, Br)

    prev_states, final_state = state_passing(states, chunk_decay, initial_state)

    y = pl.pallas_call(
        functools.partial(_chunk_output_kernel, compute_dtype=compute_dtype),
        out_shape=jax.ShapeDtypeStruct((b, nc, nhb, l, hb * p), x.dtype),
        grid=grid,
        in_specs=[xhp_spec, dt_spec, dt_spec, bc_spec, bc_spec, st_spec],
        out_specs=xhp_spec,
        compiler_params=_PARALLEL3,
        interpret=interpret,
    )(xr, dtr, ar, Br, Cr, prev_states)

    return _from_cells(y, b, t, h, p), final_state


# ---------------------------------------------------------------------------
# Backward pass (training path): Pallas kernels + tiny XLA glue.
#
# Forward decomposition per chunk (head h, in-chunk log-decay a = cumsum(dt*A)):
#   y_diag = (G .* L) @ (dt*x)      G[i,j] = <C_i, B_j>, L[i,j] = e^{a_i-a_j}
#   S      = sum_j e^{a_L-a_j} dt_j x_j (x) B_j     (per-chunk state summary)
#   P_{c+1} = gamma_c P_c + S_c,  gamma_c = e^{a_L}  (inter-chunk recurrence)
#   y_off  = diag(e^a) C @ P_c^T
# The backward mirrors it: (1) Pallas kernel for the direct state gradient
# dP_c = dY^T (e^a .* C); (2) XLA *reverse* associative scan for
# gP_c = dP_c + gamma_c gP_{c+1} (=> dS_c = gP_{c+1}, dgamma_c = <dS_c, P_c>);
# (3) one Pallas cell kernel for dx/ddt/da/dB/dC with L rebuilt in VMEM;
# (4) XLA epilogue pushing the in-chunk log-decay gradient `da` through the
# cumsum chain into ddt and dA.
# ---------------------------------------------------------------------------


def _dstate_direct_kernel(dy_ref, acum_ref, C_ref, out_ref, *, compute_dtype):
    """Direct gradient of the chunk-entering state: dP = dY^T @ (e^a .* C)."""
    a = acum_ref[0, 0, 0]                            # (l, hb) fp32
    Cb = C_ref[0, 0, 0]                              # (l, n)
    l, hb = a.shape
    dy = dy_ref[0, 0, 0].reshape(l, hb, -1)          # (l, hb, p)

    e = jnp.exp(a)                                   # (l, hb), <= 1
    eC = e.T[:, :, None] * Cb[None].astype(jnp.float32)          # (hb, l, n)
    dyt = jnp.transpose(dy, (1, 2, 0)).astype(compute_dtype)     # (hb, p, l)
    out_ref[0, 0] = jax.lax.dot_general(
        dyt, eC.astype(compute_dtype), (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                                # (hb, p, n)


def _ssd_bwd_cell_kernel(
    x_ref, dt_ref, acum_ref, B_ref, C_ref, prev_ref, dy_ref, dS_ref,
    dx_ref, ddt_ref, da_ref, dB_ref, dC_ref, *, compute_dtype,
):
    """All per-cell input gradients for one (batch, chunk, head-block).

    Outputs: dx (l,hb,p); ddt_direct (l,hb) [the dt*x product-rule term];
    da (l,hb) [grad wrt the in-chunk cumulative log-decay, pushed through
    the cumsum chain by the XLA epilogue]; dB/dC (l,n) per head-block
    [summed over a group's head-blocks outside].
    """
    cd = compute_dtype
    a = acum_ref[0, 0, 0]                            # (l, hb) fp32
    dt = dt_ref[0, 0, 0]                             # (l, hb) fp32
    l, hb = a.shape
    x = x_ref[0, 0, 0].reshape(l, hb, -1).astype(jnp.float32)    # (l, hb, p)
    Bb = B_ref[0, 0, 0]                              # (l, n)
    Cb = C_ref[0, 0, 0]                              # (l, n)
    P = prev_ref[0, 0]                               # (hb, p, n) fp32
    dy = dy_ref[0, 0, 0].reshape(l, hb, -1).astype(jnp.float32)  # (l, hb, p)
    dS = dS_ref[0, 0]                                # (hb, p, n) fp32

    e = jnp.exp(a)                                   # (l, hb)
    d = jnp.exp(a[-1:, :] - a)                       # (l, hb) decay-to-end
    u = x * dt[:, :, None]                           # (l, hb, p)
    ut = jnp.transpose(u, (1, 0, 2))                 # (hb, l, p)
    dyt = jnp.transpose(dy, (1, 0, 2))               # (hb, l, p)

    # --- intra-chunk: y_diag = (G .* L) @ u -------------------------------
    G = jnp.dot(Cb.astype(cd), Bb.astype(cd).T,
                preferred_element_type=jnp.float32)  # (l, l) group-shared
    ii = jax.lax.broadcasted_iota(jnp.int32, (l, l), 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, (l, l), 1)
    tril = ii >= jj
    diff = a.T[:, :, None] - a.T[:, None, :]         # (hb, l, l)
    Lm = jnp.exp(jnp.where(tril[None], diff, -jnp.inf))          # (hb, l, l)
    M = G[None] * Lm                                 # (hb, l, l) fp32

    dM = jax.lax.dot_general(                        # dM = dY @ u^T
        dyt.astype(cd), ut.astype(cd), (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                                # (hb, l, l)
    du = jax.lax.dot_general(                        # du = M^T @ dY
        jnp.transpose(M, (0, 2, 1)).astype(cd), dyt.astype(cd),
        (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                                # (hb, l, p)

    dMM = dM * M                                     # = dL .* L .* G
    da = (jnp.sum(dMM, axis=2) - jnp.sum(dMM, axis=1)).T         # (l, hb)
    dG = jnp.sum(dM * Lm, axis=0)                    # (l, l), masked by Lm
    dB_acc = jnp.dot(dG.T.astype(cd), Cb.astype(cd),
                     preferred_element_type=jnp.float32)         # (l, n)
    dC_acc = jnp.dot(dG.astype(cd), Bb.astype(cd),
                     preferred_element_type=jnp.float32)         # (l, n)

    # --- off-diagonal: y_off = diag(e) C @ P^T ----------------------------
    T = jax.lax.dot_general(                         # T = dY @ P
        dyt.astype(cd), P.astype(cd), (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                                # (hb, l, n)
    dC_acc = dC_acc + jnp.sum(e.T[:, :, None] * T, axis=0)
    de = jnp.sum(T * Cb[None].astype(jnp.float32), axis=2)       # (hb, l)
    da = da + de.T * e

    # --- state summary: S = sum_j d_j u_j (x) B_j -------------------------
    dwt = jnp.transpose(                             # dw = dS @ B^T per head
        jax.lax.dot_general(
            dS.astype(cd), Bb.astype(cd), (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ),                                           # (hb, p, l)
        (0, 2, 1),
    )                                                # (hb, l, p)
    dT = d.T                                         # (hb, l)
    wt = ut * dT[:, :, None]                         # (hb, l, p)
    dB_acc = dB_acc + jnp.sum(
        jax.lax.dot_general(
            jnp.transpose(wt, (0, 2, 1)).astype(cd), dS.astype(cd),
            (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ),
        axis=0,
    )                                                # (l, n)
    du = du + dT[:, :, None] * dwt
    dd = jnp.sum(ut * dwt, axis=2)                   # (hb, l)
    ddd = dd * dT                                    # chain through exp
    da = da - ddd.T
    # += at the last row, as a mask-add (scatter has no Mosaic lowering)
    last = (jax.lax.broadcasted_iota(jnp.int32, da.shape, 0) == l - 1)
    da = da + jnp.where(last, jnp.sum(ddd, axis=1)[None, :], 0.0)

    # --- u = dt * x product rule ------------------------------------------
    du_l = jnp.transpose(du, (1, 0, 2))              # (l, hb, p)
    dx_ref[0, 0, 0] = (dt[:, :, None] * du_l).reshape(l, -1).astype(dx_ref.dtype)
    ddt_ref[0, 0, 0] = jnp.sum(x * du_l, axis=2)
    da_ref[0, 0, 0] = da
    dB_ref[0, 0, 0] = dB_acc
    dC_ref[0, 0, 0] = dC_acc


def _ssd_pallas_bwd_impl(
    x, dt, A, B, C, dy, chunk_size, compute_dtype, interpret,
    initial_state=None, dfinal=None,
):
    """Full backward: recompute chunk states, reverse-scan, cell kernel.

    ``initial_state`` (b, h, p, n) makes the recomputed entering states
    match a forward that was seeded (decode prefill / SP shards), and its
    gradient is returned as the sixth output.  ``dfinal`` is the cotangent
    of the final state when the forward returned it; it seeds the reverse
    state scan the same way ``initial_state`` seeds the forward one.
    """
    l0 = _divisor_chunk(x.shape[1], chunk_size)
    xr, dtr, ar, chunk_decay, Br, Cr, dims = _chunked_inputs(
        x, dt, A, B, C, chunk_size, max_hb=_bwd_hb_cap(l0)
    )
    b, nc, l, h, hb, p, g, n = dims
    t = nc * l
    nhb = h // hb
    grid = (b, nc, nhb)
    xhp_spec, dt_spec, bc_spec, st_spec = _cell_specs(h, hb, l, p, n, g)
    dyr = _to_cells(dy, b, nc, l, nhb, hb, (p,))

    # recompute the chunk summaries + entering states (remat, like the
    # reference dep's Triton backward which re-derives chunk states)
    states = pl.pallas_call(
        functools.partial(_chunk_states_kernel, compute_dtype=compute_dtype),
        out_shape=jax.ShapeDtypeStruct((b, nc, h, p, n), jnp.float32),
        grid=grid,
        in_specs=[xhp_spec, dt_spec, dt_spec, bc_spec],
        out_specs=st_spec,
        compiler_params=_PARALLEL3,
        interpret=interpret,
    )(xr, dtr, ar, Br)
    prev_states, _ = state_passing(states, chunk_decay, initial_state)

    # direct state gradient from each chunk's off-diagonal output
    dP = pl.pallas_call(
        functools.partial(_dstate_direct_kernel, compute_dtype=compute_dtype),
        out_shape=jax.ShapeDtypeStruct((b, nc, h, p, n), jnp.float32),
        grid=grid,
        in_specs=[xhp_spec, dt_spec, bc_spec],
        out_specs=st_spec,
        compiler_params=_PARALLEL3,
        interpret=interpret,
    )(dyr, ar, Cr)

    # reverse associative scan: gP_c = dP_c + gamma_c * gP_{c+1}.  A final-
    # state cotangent seeds it as a virtual chunk nc with dP = dfinal (its
    # own decay entry is never consumed), so gP_c picks up the
    # prod(gamma)-propagated dfinal term for free.
    decay = chunk_decay[..., None, None]             # (b, nc, h, 1, 1)
    if dfinal is not None:
        dP = jnp.concatenate(
            [dP, dfinal.astype(dP.dtype)[:, None]], axis=1
        )
        decay = jnp.concatenate([decay, jnp.ones_like(decay[:, :1])], axis=1)

    def combine(left, right):
        a_l, s_l = left
        a_r, s_r = right
        return a_l * a_r, s_l * a_r + s_r

    _, gP_rev = jax.lax.associative_scan(
        combine, (jnp.flip(decay, 1), jnp.flip(dP, 1)), axis=1
    )
    gP = jnp.flip(gP_rev, 1)                         # (b, nc(+1), h, p, n)
    if dfinal is not None:
        dS = gP[:, 1:]                               # virtual chunk = dfinal
    else:
        dS = jnp.concatenate([gP[:, 1:], jnp.zeros_like(gP[:, :1])], axis=1)
    # gradient wrt the state entering chunk 0 == wrt initial_state
    dinit = gP[:, 0] if initial_state is not None else None
    dgamma = jnp.sum(dS * prev_states, axis=(3, 4))  # (b, nc, h)

    dx_c, ddt5, da5, dB_cell, dC_cell = pl.pallas_call(
        functools.partial(_ssd_bwd_cell_kernel, compute_dtype=compute_dtype),
        out_shape=(
            jax.ShapeDtypeStruct((b, nc, nhb, l, hb * p), x.dtype),
            jax.ShapeDtypeStruct((b, nc, nhb, l, hb), jnp.float32),
            jax.ShapeDtypeStruct((b, nc, nhb, l, hb), jnp.float32),
            jax.ShapeDtypeStruct((b, nc, nhb, l, n), jnp.float32),
            jax.ShapeDtypeStruct((b, nc, nhb, l, n), jnp.float32),
        ),
        grid=grid,
        in_specs=[xhp_spec, dt_spec, dt_spec, bc_spec, bc_spec, st_spec,
                  xhp_spec, st_spec],
        out_specs=(
            xhp_spec,
            dt_spec,
            dt_spec,
            pl.BlockSpec((1, 1, 1, l, n), lambda bi, ci, hi: (bi, ci, hi, 0, 0)),
            pl.BlockSpec((1, 1, 1, l, n), lambda bi, ci, hi: (bi, ci, hi, 0, 0)),
        ),
        compiler_params=_PARALLEL3,
        interpret=interpret,
    )(xr, dtr, ar, Br, Cr, prev_states, dyr, dS)

    # --- XLA epilogue: push `da` through the cumsum chain -----------------
    def cells_to_blh(v):  # (b, nc, nhb, l, hb) -> (b, nc, l, h)
        return jnp.moveaxis(v, 2, 3).reshape(b, nc, l, h)

    da = cells_to_blh(da5)
    ddt_dir = cells_to_blh(ddt5)
    da = da.at[:, :, -1, :].add(dgamma * chunk_decay)
    ddA = jnp.flip(jnp.cumsum(jnp.flip(da, 2), axis=2), 2)       # (b, nc, l, h)
    Af = A.astype(jnp.float32)
    ddt = (ddt_dir + ddA * Af[None, None, None]).reshape(b, t, h)
    dA = jnp.sum(ddA * cells_to_blh(dtr), axis=(0, 1, 2))

    # group-sum the per-head-block B/C gradients (blocks are head-ordered,
    # so a group's nhb/g blocks are consecutive)
    dB_g = dB_cell.reshape(b, nc, g, nhb // g, l, n).sum(axis=3)
    dC_g = dC_cell.reshape(b, nc, g, nhb // g, l, n).sum(axis=3)
    dB = jnp.transpose(dB_g, (0, 1, 3, 2, 4)).reshape(b, t, g, n)
    dC = jnp.transpose(dC_g, (0, 1, 3, 2, 4)).reshape(b, t, g, n)

    return (
        _from_cells(dx_c, b, t, h, p),
        ddt.astype(dt.dtype),
        dA.astype(A.dtype),
        dB.astype(B.dtype),
        dC.astype(C.dtype),
        dinit,
    )


def _add_D(y, x, D):
    if D is None:
        return y
    Df = D.astype(jnp.float32)
    yf = y.astype(jnp.float32) + x.astype(jnp.float32) * (
        Df[None, None, :, :] if Df.ndim == 2 else Df[None, None, :, None]
    )
    return yf.astype(x.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9)
)
def _ssd_pallas_core(
    x, dt, A, B, C, initial_state, chunk_size, compute_dtype, interpret,
    return_final_state,
):
    y, final = _ssd_pallas_fwd_impl(
        x, dt, A, B, C, chunk_size, initial_state, compute_dtype, interpret
    )
    return (y, final) if return_final_state else y


def _core_fwd(
    x, dt, A, B, C, initial_state, chunk_size, compute_dtype, interpret,
    return_final_state,
):
    out = _ssd_pallas_core(
        x, dt, A, B, C, initial_state, chunk_size, compute_dtype, interpret,
        return_final_state,
    )
    return out, (x, dt, A, B, C, initial_state)


def _core_bwd(chunk_size, compute_dtype, interpret, return_final_state, res, ct):
    """Pallas backward (see the backward section above)."""
    x, dt, A, B, C, initial_state = res
    dy, dfinal = ct if return_final_state else (ct, None)
    dx, ddt, dA, dB, dC, dinit = _ssd_pallas_bwd_impl(
        x, dt, A, B, C, dy, chunk_size, compute_dtype, interpret,
        initial_state=initial_state, dfinal=dfinal,
    )
    return dx, ddt, dA, dB, dC, dinit


_ssd_pallas_core.defvjp(_core_fwd, _core_bwd)


def ssd_chunked_pallas(
    x: jax.Array,
    dt: jax.Array,
    A: jax.Array,
    B: jax.Array,
    C: jax.Array,
    chunk_size: int = 256,
    D: jax.Array | None = None,
    initial_state: jax.Array | None = None,
    return_final_state: bool = False,
    compute_dtype=jnp.bfloat16,
    interpret: bool | None = None,
):
    """Drop-in for ops/ssd.ssd_chunked backed by Pallas kernels.

    Every path — plain training, seeded (``initial_state``: decode
    prefill / SP shards), and ``return_final_state`` — runs under the
    custom VJP whose backward is itself Pallas (kernels above): the
    seeded forward recomputes entering states from the same seed, a
    final-state cotangent seeds the reverse state scan, and the
    initial-state gradient comes back as ``gP[0]``.  ``interpret=None``
    auto-selects the Pallas interpreter off-TPU (CPU tests run the same
    kernel code).
    """
    interpret = resolve_interpret(interpret)
    out = _ssd_pallas_core(
        x, dt, A, B, C, initial_state, chunk_size, compute_dtype, interpret,
        return_final_state,
    )
    if return_final_state:
        y, final_state = out
        return _add_D(y, x, D), final_state
    return _add_D(out, x, D)
