"""Pallas SSD (Mamba-2) chunked-scan kernels.

TPU-native counterpart of the Triton SSD kernels the reference depends on
(``mamba_ssm/ops/triton/ssd_chunk_scan.py`` etc., mamba-ssm 2.2.2) — but
re-derived for the MXU/VMEM model, not translated:

  * one grid cell = (batch, chunk, head-block); the (l x l) decay matrix
    ``L`` is rebuilt from the cumulative log-decay *inside VMEM* per cell,
    never touching HBM (the XLA path's biggest intermediate);
  * the two sequential pieces stay at the XLA level where they belong:
    the inter-chunk state recurrence is a tiny ``associative_scan``
    (ops/ssd.state_passing), and grouped B/C are indexed per head-block
    via the BlockSpec index map (never repeated into (b, t, h, n) form);
  * heads are processed ``hb = 128 // headdim`` at a time so the lane
    dimension of the y/x tiles stays full.

Training uses ``jax.custom_vjp``: the backward runs the einsum
formulation (exact same math; XLA autodiff), so gradients are identical
to ``ssd_chunked`` — pinned by tests/test_pallas.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mamba_distributed_tpu.ops.scan import _divisor_chunk
from mamba_distributed_tpu.ops.ssd import state_passing

# every grid cell is independent — let both megacore TensorCores split it
_PARALLEL3 = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel"),
)


def _chunk_states_kernel(x_ref, dt_ref, acum_ref, B_ref, out_ref, *, compute_dtype):
    """Per-chunk state contribution: out[hb, p, n] = sum_l decay*dt*x (x) B."""
    a = acum_ref[0, 0]            # (l, hb) fp32, inclusive cumsum of dt*A
    dt = dt_ref[0, 0]             # (l, hb) fp32
    Bb = B_ref[0, 0, :, 0]        # (l, n)
    x = x_ref[0, 0]               # (l, hb, p)

    decay = jnp.exp(a[-1:, :] - a) * dt            # (l, hb)
    Bd = Bb[:, None, :] * decay[:, :, None]        # (l, hb, n)
    # batched over hb: (hb, p, l) @ (hb, l, n) -> (hb, p, n)
    xt = jnp.transpose(x, (1, 2, 0)).astype(compute_dtype)
    Bt = jnp.transpose(Bd, (1, 0, 2)).astype(compute_dtype)
    out_ref[0, 0] = jax.lax.dot_general(
        xt, Bt, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )


def _chunk_output_kernel(
    x_ref, dt_ref, acum_ref, B_ref, C_ref, prev_ref, y_ref, *, compute_dtype
):
    """y = (G odot L) @ (x*dt) + (C*exp(a)) @ prev_state^T for one cell."""
    a = acum_ref[0, 0]            # (l, hb) fp32
    dt = dt_ref[0, 0]             # (l, hb)
    Bb = B_ref[0, 0, :, 0].astype(compute_dtype)   # (l, n)
    Cb = C_ref[0, 0, :, 0].astype(compute_dtype)   # (l, n)
    x = x_ref[0, 0]               # (l, hb, p)
    prev = prev_ref[0, 0]         # (hb, p, n) fp32
    l = a.shape[0]

    # G is group-shared across the hb heads of this block
    G = jnp.dot(Cb, Bb.T, preferred_element_type=jnp.float32)  # (l, l)

    # decay matrix rebuilt in VMEM: L[h, i, j] = exp(a_i - a_j) on i >= j
    ai = a.T[:, :, None]          # (hb, l, 1)
    aj = a.T[:, None, :]          # (hb, 1, l)
    ii = jax.lax.broadcasted_iota(jnp.int32, (l, l), 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, (l, l), 1)
    tril = ii >= jj
    M = jnp.where(tril[None], G[None] * jnp.exp(ai - aj), 0.0)  # (hb, l, l)

    xdt = (x.astype(jnp.float32) * dt[:, :, None]).astype(compute_dtype)
    xdt_t = jnp.transpose(xdt, (1, 0, 2))          # (hb, l, p)
    y = jax.lax.dot_general(
        M.astype(compute_dtype), xdt_t, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                              # (hb, l, p)

    # off-diagonal: carried-state contribution
    cd = (Cb[None] * jnp.exp(a.T)[:, :, None]).astype(compute_dtype)  # (hb, l, n)
    y = y + jax.lax.dot_general(
        cd, jnp.transpose(prev, (0, 2, 1)).astype(compute_dtype),
        (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    y_ref[0, 0] = jnp.transpose(y, (1, 0, 2)).astype(y_ref.dtype)  # (l, hb, p)


def _heads_per_block(h: int, p: int, g: int) -> int:
    hb = max(1, 128 // p)
    heads_per_group = h // g
    while heads_per_group % hb != 0 or h % hb != 0:
        hb -= 1
    return max(hb, 1)


def _ssd_pallas_fwd_impl(
    x, dt, A, B, C, chunk_size, initial_state, compute_dtype, interpret
):
    """Forward via the two kernels + XLA state passing.

    Shapes: x (b,t,h,p); dt (b,t,h) [bias-added+softplused]; A (h,);
    B/C (b,t,g,n).  Returns (y_no_D (b,t,h,p) fp32-accurate, final_state).
    """
    b, t, h, p = x.shape
    g, n = B.shape[2], B.shape[3]
    l = _divisor_chunk(t, chunk_size)
    nc = t // l
    hb = _heads_per_block(h, p, g)
    nhb = h // hb

    dtf = dt.astype(jnp.float32)
    dA = dtf * A.astype(jnp.float32)                 # (b, t, h)
    dAc = dA.reshape(b, nc, l, h)
    a_cum = jnp.cumsum(dAc, axis=2)                  # (b, nc, l, h)
    chunk_decay = jnp.exp(a_cum[:, :, -1, :])        # (b, nc, h)

    xr = x.reshape(b, nc, l, h, p)
    dtr = dtf.reshape(b, nc, l, h)
    Br = B.reshape(b, nc, l, g, n)
    Cr = C.reshape(b, nc, l, g, n)

    grid = (b, nc, nhb)
    # index maps: (bi, ci, hi) -> block indices; B/C pick the head-block's group
    x_spec = pl.BlockSpec((1, 1, l, hb, p), lambda bi, ci, hi: (bi, ci, 0, hi, 0))
    dt_spec = pl.BlockSpec((1, 1, l, hb), lambda bi, ci, hi: (bi, ci, 0, hi))
    bc_spec = pl.BlockSpec(
        (1, 1, l, 1, n), lambda bi, ci, hi: (bi, ci, 0, (hi * hb * g) // h, 0)
    )

    states = pl.pallas_call(
        functools.partial(_chunk_states_kernel, compute_dtype=compute_dtype),
        out_shape=jax.ShapeDtypeStruct((b, nc, h, p, n), jnp.float32),
        grid=grid,
        in_specs=[x_spec, dt_spec, dt_spec, bc_spec],
        out_specs=pl.BlockSpec(
            (1, 1, hb, p, n), lambda bi, ci, hi: (bi, ci, hi, 0, 0)
        ),
        compiler_params=_PARALLEL3,
        interpret=interpret,
    )(xr, dtr, a_cum, Br)

    prev_states, final_state = state_passing(states, chunk_decay, initial_state)

    y = pl.pallas_call(
        functools.partial(_chunk_output_kernel, compute_dtype=compute_dtype),
        out_shape=jax.ShapeDtypeStruct((b, nc, l, h, p), x.dtype),
        grid=grid,
        in_specs=[
            x_spec, dt_spec, dt_spec, bc_spec, bc_spec,
            pl.BlockSpec((1, 1, hb, p, n), lambda bi, ci, hi: (bi, ci, hi, 0, 0)),
        ],
        out_specs=x_spec,
        compiler_params=_PARALLEL3,
        interpret=interpret,
    )(xr, dtr, a_cum, Br, Cr, prev_states)

    return y.reshape(b, t, h, p), final_state


def _add_D(y, x, D):
    if D is None:
        return y
    Df = D.astype(jnp.float32)
    yf = y.astype(jnp.float32) + x.astype(jnp.float32) * (
        Df[None, None, :, :] if Df.ndim == 2 else Df[None, None, :, None]
    )
    return yf.astype(x.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7)
)
def _ssd_pallas_core(x, dt, A, B, C, chunk_size, compute_dtype, interpret):
    y, _ = _ssd_pallas_fwd_impl(
        x, dt, A, B, C, chunk_size, None, compute_dtype, interpret
    )
    return y


def _core_fwd(x, dt, A, B, C, chunk_size, compute_dtype, interpret):
    y = _ssd_pallas_core(x, dt, A, B, C, chunk_size, compute_dtype, interpret)
    return y, (x, dt, A, B, C)


def _core_bwd(chunk_size, compute_dtype, interpret, res, dy):
    """Backward through the einsum formulation — same math, XLA autodiff."""
    from mamba_distributed_tpu.ops.ssd import ssd_chunked

    x, dt, A, B, C = res

    def f(x, dt, A, B, C):
        # dt here is already softplus-ed; ssd_chunked takes it as-is
        return ssd_chunked(
            x, dt, A, B, C, chunk_size=chunk_size, D=None,
            compute_dtype=compute_dtype,
        )

    _, vjp = jax.vjp(f, x, dt, A, B, C)
    return vjp(dy)


_ssd_pallas_core.defvjp(_core_fwd, _core_bwd)


def ssd_chunked_pallas(
    x: jax.Array,
    dt: jax.Array,
    A: jax.Array,
    B: jax.Array,
    C: jax.Array,
    chunk_size: int = 256,
    D: jax.Array | None = None,
    initial_state: jax.Array | None = None,
    return_final_state: bool = False,
    compute_dtype=jnp.bfloat16,
    interpret: bool | None = None,
):
    """Drop-in for ops/ssd.ssd_chunked backed by Pallas kernels.

    With ``return_final_state`` or ``initial_state`` (decode prefill / SP)
    the non-custom-vjp path is used; the training path (neither) gets the
    custom VJP with an XLA backward.  ``interpret=None`` auto-selects the
    Pallas interpreter off-TPU (CPU tests run the same kernel code).
    """
    if interpret is None:
        # real Mosaic lowering on TPU (incl. tunneled platforms whose
        # backend name isn't "tpu"); interpreter elsewhere (CPU tests)
        kind = getattr(jax.devices()[0], "device_kind", "").lower()
        interpret = not (jax.default_backend() == "tpu" or "tpu" in kind)
    if initial_state is None and not return_final_state:
        y = _ssd_pallas_core(
            x, dt, A, B, C, chunk_size, compute_dtype, interpret
        )
        return _add_D(y, x, D)
    y, final_state = _ssd_pallas_fwd_impl(
        x, dt, A, B, C, chunk_size, initial_state, compute_dtype, interpret
    )
    y = _add_D(y, x, D)
    if return_final_state:
        return y, final_state
    return y
