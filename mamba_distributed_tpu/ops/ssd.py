"""SSD chunked scan (Mamba-2 "state-space duality"), TPU-native.

Equivalent of the reference dependency's Triton SSD kernels
(``mamba_ssm/ops/triton/ssd_combined.py``, ``ssd_chunk_scan.py``,
``ssd_chunk_state.py``, ``ssd_state_passing.py``, ``ssd_bmm.py`` in
mamba-ssm 2.2.2, pinned at reference requirements.txt:2).

The algorithm is re-derived for the MXU rather than translated: the sequence
is split into chunks of length L; within a chunk the recurrence is expressed
as batched (L x N) @ (N x L) and (L x L) @ (L x P) matmuls (pure MXU work),
while the tiny per-chunk states (H, P, N) flow through an associative scan
over chunks.  The same per-chunk state decomposition is what sequence
parallelism rides on (each device computes its local chunk states; only the
(H, P, N) boundary states cross devices — see parallel/seq_parallel.py and
SURVEY.md section 5).

Recurrence (per batch, head h, state n, head-channel p):
    h_t = exp(dt_t * A_h) * h_{t-1} + dt_t * B_t  x_t^T        (outer product)
    y_t = C_t . h_t + D_h * x_t

Shapes (group g broadcasts over the heads it owns, heads-per-group = H/G):
    x  (b, t, h, p)      dt (b, t, h)   [already bias-added + softplus-ed]
    A  (h,) negative     B, C (b, t, g, n)
    D  (h,) or (h, p)    initial_state (b, h, p, n)

Decay math runs in fp32 (differences of cumulative log-decays stay <= 0, so
exp() never overflows); the big matmuls run in the compute dtype with fp32
accumulation (``preferred_element_type``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cumsum_mxu(x: jax.Array, axis: int = -1, reverse: bool = False) -> jax.Array:
    """Inclusive (reverse-)cumsum as a triangular matmul.

    ``jnp.cumsum`` lowers to a sequential reduce-window on TPU (measured
    ~25 ms/step across the 280M model's four cumsum sites, round-4 trace);
    a (l, l) lower-triangular ones matmul computes the same prefix sums on
    the MXU at negligible cost and fuses with the surrounding decay math.
    The transposed triangle gives the reverse cumsum, so the custom-vjp-free
    gradient (a reverse cumsum) rides the MXU too.
    """
    l = x.shape[axis]
    tri = jnp.tril(jnp.ones((l, l), jnp.float32))
    if reverse:
        tri = tri.T
    xm = jnp.moveaxis(x, axis, -1)
    out = jnp.einsum(
        "...s,ls->...l", xm.astype(jnp.float32), tri,
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    return jnp.moveaxis(out, -1, axis)


def segsum(x: jax.Array) -> jax.Array:
    """Segment-sum: out[..., i, j] = sum_{k in (j, i]} x[..., k] for i >= j.

    Returns -inf above the diagonal so that exp(segsum) is the lower-
    triangular decay matrix with ones on the diagonal.
    """
    l = x.shape[-1]
    cs = cumsum_mxu(x, axis=-1)
    d = cs[..., :, None] - cs[..., None, :]
    mask = jnp.tril(jnp.ones((l, l), dtype=bool))
    return jnp.where(mask, d, -jnp.inf)


def _expand_groups(BC: jax.Array, nheads: int) -> jax.Array:
    """(b, t, g, n) -> (b, t, h, n) by repeating each group over its heads."""
    g = BC.shape[2]
    if g == nheads:
        return BC
    assert nheads % g == 0
    return jnp.repeat(BC, nheads // g, axis=2)


def ssd_seq(
    x: jax.Array,
    dt: jax.Array,
    A: jax.Array,
    B: jax.Array,
    C: jax.Array,
    D: jax.Array | None = None,
    initial_state: jax.Array | None = None,
    return_final_state: bool = False,
):
    """Oracle: sequential scan over time (fp32 throughout)."""
    b, t, h, p = x.shape
    n = B.shape[-1]
    xf = x.astype(jnp.float32)
    dtf = dt.astype(jnp.float32)
    Af = A.astype(jnp.float32)
    Bf = _expand_groups(B, h).astype(jnp.float32)
    Cf = _expand_groups(C, h).astype(jnp.float32)

    s0 = (
        jnp.zeros((b, h, p, n), jnp.float32)
        if initial_state is None
        else initial_state.astype(jnp.float32)
    )

    def step(s, inputs):
        x_t, dt_t, B_t, C_t = inputs  # (b,h,p) (b,h) (b,h,n) (b,h,n)
        decay = jnp.exp(dt_t * Af[None])  # (b, h)
        s = s * decay[:, :, None, None] + jnp.einsum(
            "bhp,bhn,bh->bhpn", x_t, B_t, dt_t
        )
        y_t = jnp.einsum("bhpn,bhn->bhp", s, C_t)
        return s, y_t

    xs = (
        jnp.moveaxis(xf, 1, 0),
        jnp.moveaxis(dtf, 1, 0),
        jnp.moveaxis(Bf, 1, 0),
        jnp.moveaxis(Cf, 1, 0),
    )
    s_last, ys = jax.lax.scan(step, s0, xs)
    y = jnp.moveaxis(ys, 0, 1)
    if D is not None:
        Df = D.astype(jnp.float32)
        y = y + xf * (Df[None, None, :, :] if Df.ndim == 2 else Df[None, None, :, None])
    y = y.astype(x.dtype)
    if return_final_state:
        return y, s_last
    return y


def chunk_local(
    x: jax.Array,
    dt: jax.Array,
    A: jax.Array,
    B: jax.Array,
    C: jax.Array,
    chunk_size: int,
    compute_dtype=jnp.bfloat16,
):
    """Per-chunk compute: diagonal-block outputs + chunk summaries.

    This is the device-local portion of SSD — everything except the
    inter-chunk state recurrence.  Sequence parallelism calls this on the
    local shard and runs the state recurrence across devices.

    Returns:
      y_diag       (b, nc, l, h, p) intra-chunk contribution
      states       (b, nc, h, p, n) per-chunk final state contribution
      chunk_decay  (b, nc, h)       exp(sum of dt*A over the chunk)
      off_ctx      (C (b, nc, l, g, n) compute-dtype, state_decay
                   (b, nc, l, h) fp32) — inputs to the off-diagonal
                   correction (combine_chunk_outputs)

    B and C stay in their group-compact (g, n) form throughout: the G
    Gram matrix is computed once per group (h/g-fold fewer MACs than the
    per-head formulation), per-head decay scalars attach to the tensors
    that are already per-head (x, the off-diagonal output), and nothing
    of shape (b, t, h, n) is ever materialized.
    """
    b, t, h, p = x.shape
    g, n = B.shape[2], B.shape[-1]
    assert h % g == 0
    hg = h // g
    l = chunk_size
    assert t % l == 0, (t, l)
    nc = t // l

    dtf = dt.astype(jnp.float32)
    Af = A.astype(jnp.float32)

    xc = x.reshape(b, nc, l, h, p)
    dtc = dtf.reshape(b, nc, l, h)
    Bc = B.reshape(b, nc, l, g, n)
    Cc = C.reshape(b, nc, l, g, n)

    dA = dtc * Af  # (b, nc, l, h), <= 0
    dA_cum = cumsum_mxu(dA, axis=2)  # inclusive cumsum within chunk

    # --- intra-chunk (diagonal blocks): batched MXU matmuls ---
    # G[i, j] = <C_i, B_j> is group-shared -> (b, nc, g, l, l)
    G = jnp.einsum(
        "bclgn,bcsgn->bcgls",
        Cc.astype(compute_dtype),
        Bc.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    # decay math in fp32 (exp of <=0 stays stable), but the (l x l) masked
    # decay matrix — the biggest intermediate of the whole op, O(b*t*h*l) —
    # is materialized in the compute dtype to halve its HBM traffic
    L_mat = jnp.exp(segsum(jnp.moveaxis(dA, 2, -1)))  # (b, nc, h, l, l)
    Lg = L_mat.reshape(b, nc, g, hg, l, l)
    M = (G[:, :, :, None] * Lg).astype(compute_dtype).reshape(b, nc, h, l, l)
    xdt = (xc.astype(jnp.float32) * dtc[..., None]).astype(compute_dtype)
    y_diag = jnp.einsum(
        "bchls,bcshp->bclhp",
        M,
        xdt,
        preferred_element_type=jnp.float32,
    )

    # --- per-chunk state summaries (per-head decay*dt attaches to x) ---
    decay_states = jnp.exp(dA_cum[:, :, -1:, :] - dA_cum)  # (b, nc, l, h)
    xg = (
        (xc.astype(jnp.float32) * (decay_states * dtc)[..., None])
        .astype(compute_dtype)
        .reshape(b, nc, l, g, hg, p)
    )
    states = jnp.einsum(
        "bclgn,bclgjp->bcgjpn",
        Bc.astype(compute_dtype),
        xg,
        preferred_element_type=jnp.float32,
    ).reshape(b, nc, h, p, n)
    chunk_decay = jnp.exp(dA_cum[:, :, -1, :])  # (b, nc, h)
    off_ctx = (Cc.astype(compute_dtype), jnp.exp(dA_cum))
    return y_diag, states, chunk_decay, off_ctx


# Above this chunk count the O(nc^2) decay-weight einsum in state_passing
# yields to the O(log nc) associative scan (tests force the fallback by
# patching this).
_STATE_PASSING_EINSUM_MAX_NC = 256


def state_passing(
    states: jax.Array,
    chunk_decay: jax.Array,
    initial_state: jax.Array | None = None,
):
    """Inter-chunk state recurrence via associative scan.

    states (b, nc, h, p, n); chunk_decay (b, nc, h).
    Returns (prev_states (b, nc, h, p, n) — the state *entering* each chunk —
    and final_state (b, h, p, n)).
    """
    b, nc, h, p, n = states.shape
    if nc <= _STATE_PASSING_EINSUM_MAX_NC:
        # Dominant path: the recurrence as one lower-triangular decay-
        # weighted einsum on the MXU.  The associative_scan formulation
        # pads/slices the full (b, nc, h, p, n) array every round (six
        # whole-array pad ops ≈ 44 ms/step on the 280M config, round-4
        # trace); the matmul is O(nc^2) in tiny chunk counts and touches
        # each state tensor exactly once.  Log-space decays keep it exact:
        # cum is non-increasing, so every exp argument is <= 0.  Clamping
        # at fp32-tiny only affects per-chunk decays that already
        # underflowed to zero, where exp(diff) underflows to zero too.
        ldc = jnp.log(
            jnp.maximum(
                chunk_decay.astype(jnp.float32), jnp.finfo(jnp.float32).tiny
            )
        )
        cum = cumsum_mxu(ldc, axis=1)  # (b, nc, h)
        # W[c, j] = prod of decays (j, c] = exp(cum[c] - cum[j]) for j <= c
        diff = cum[:, :, None, :] - cum[:, None, :, :]  # (b, c, j, h)
        tri = jnp.tril(jnp.ones((nc, nc), dtype=bool))[None, :, :, None]
        # double-where: above the diagonal diff >= 0 can overflow exp and
        # the dead branch would still NaN the gradient
        safe = jnp.where(tri, diff, -100.0)
        W = jnp.where(tri, jnp.exp(safe), 0.0).astype(states.dtype)
        s_cum = jnp.einsum(
            "bcjh,bjhpn->bchpn", W, states,
            preferred_element_type=jnp.float32,
        ).astype(states.dtype)
        if initial_state is not None:
            s0 = initial_state.astype(jnp.float32)[:, None]
            a_cum = jnp.exp(cum)[..., None, None].astype(jnp.float32)
            s_cum = (s_cum.astype(jnp.float32) + a_cum * s0).astype(
                states.dtype
            )
    else:
        decay = chunk_decay[..., None, None]  # (b, nc, h, 1, 1)

        def combine(left, right):
            a_l, s_l = left
            a_r, s_r = right
            # a stays (b, nc, h, 1, 1); broadcast only against states
            return a_l * a_r, s_l * a_r + s_r

        a_cum, s_cum = jax.lax.associative_scan(
            combine, (decay, states), axis=1
        )
        # s_cum[c] = state *after* chunk c assuming zero initial state.
        if initial_state is not None:
            s0 = initial_state.astype(states.dtype)[:, None]
            s_cum = s_cum + a_cum * s0
    final_state = s_cum[:, -1]
    # state entering chunk c = s_cum[c-1]; chunk 0 gets the initial state.
    s0_in = (
        jnp.zeros((b, 1, h, p, n), states.dtype)
        if initial_state is None
        else initial_state.astype(states.dtype)[:, None]
    )
    prev_states = jnp.concatenate([s0_in, s_cum[:, :-1]], axis=1)
    return prev_states, final_state


def combine_chunk_outputs(
    y_diag: jax.Array,
    off_ctx: tuple[jax.Array, jax.Array],
    prev_states: jax.Array,
    x: jax.Array,
    D: jax.Array | None,
    compute_dtype,
) -> jax.Array:
    """Assemble the SSD output from per-chunk pieces.

    Shared by the single-device path (ssd_chunked) and the sequence-
    parallel path (parallel/seq_parallel.sp_ssd): off-diagonal correction
    through the carried states + optional D skip connection.  The per-head
    decay scalar multiplies the einsum *output*, so C never expands past
    its group-compact form.
    """
    b, nc, l, h, p = y_diag.shape
    Cc, state_decay = off_ctx  # (b, nc, l, g, n), (b, nc, l, h)
    g = Cc.shape[3]
    n = prev_states.shape[-1]
    prev_g = prev_states.reshape(b, nc, g, h // g, p, n)
    y_off = jnp.einsum(
        "bclgn,bcgjpn->bclgjp",
        Cc.astype(compute_dtype),
        prev_g.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    ).reshape(b, nc, l, h, p)
    y_off = y_off * state_decay[..., None]
    y = (y_diag + y_off).reshape(b, nc * l, h, p)
    if D is not None:
        Df = D.astype(jnp.float32)
        y = y + x.astype(jnp.float32) * (
            Df[None, None, :, :] if Df.ndim == 2 else Df[None, None, :, None]
        )
    return y.astype(x.dtype)


def ssd_chunked(
    x: jax.Array,
    dt: jax.Array,
    A: jax.Array,
    B: jax.Array,
    C: jax.Array,
    chunk_size: int = 256,
    D: jax.Array | None = None,
    initial_state: jax.Array | None = None,
    return_final_state: bool = False,
    compute_dtype=jnp.bfloat16,
):
    """Full chunked SSD forward (single device).

    Wall-to-wall: chunk_local -> state_passing -> off-diagonal correction.
    Autodiff-friendly; the backward pass is XLA-derived from the same matmul
    graph (all matmuls, so it stays on the MXU).
    """
    from mamba_distributed_tpu.ops.scan import _divisor_chunk

    b, t, h, p = x.shape
    l = _divisor_chunk(t, chunk_size)

    y_diag, states, chunk_decay, off_ctx = chunk_local(
        x, dt, A, B, C, l, compute_dtype
    )
    prev_states, final_state = state_passing(states, chunk_decay, initial_state)
    y = combine_chunk_outputs(y_diag, off_ctx, prev_states, x, D, compute_dtype)
    if return_final_state:
        return y, final_state
    return y


def ssd_state_update(
    ssm_state: jax.Array,
    x_t: jax.Array,
    dt_t: jax.Array,
    A: jax.Array,
    B_t: jax.Array,
    C_t: jax.Array,
    D: jax.Array | None = None,
    dt_bias: jax.Array | None = None,
    dt_softplus: bool = True,
):
    """O(1)-per-token recurrent step for decode (Mamba-2 shapes).

    Equivalent of ``selective_state_update`` applied to the multi-head SSD
    state.  ssm_state (b, h, p, n); x_t (b, h, p); dt_t (b, h);
    B_t/C_t (b, g, n).  Returns (y_t (b, h, p), new_state).
    """
    b, h, p, n = ssm_state.shape
    sf = ssm_state.astype(jnp.float32)
    xf = x_t.astype(jnp.float32)
    dtf = dt_t.astype(jnp.float32)
    if dt_bias is not None:
        dtf = dtf + dt_bias.astype(jnp.float32)
    if dt_softplus:
        dtf = jax.nn.softplus(dtf)
    Bh = _expand_groups(B_t[:, None], h)[:, 0].astype(jnp.float32)  # (b, h, n)
    Ch = _expand_groups(C_t[:, None], h)[:, 0].astype(jnp.float32)
    decay = jnp.exp(dtf * A.astype(jnp.float32)[None])  # (b, h)
    s = sf * decay[:, :, None, None] + jnp.einsum("bhp,bhn,bh->bhpn", xf, Bh, dtf)
    y = jnp.einsum("bhpn,bhn->bhp", s, Ch)
    if D is not None:
        Df = D.astype(jnp.float32)
        y = y + xf * (Df[None] if Df.ndim == 2 else Df[None, :, None])
    return y.astype(x_t.dtype), s
