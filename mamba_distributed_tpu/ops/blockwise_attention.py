"""Blockwise (flash-style) causal attention — online softmax over KV blocks.

Replaces the full (t, t) fp32 score tensor of a naive SDPA with an
O(t * block) working set: the KV sequence is consumed block-by-block
under ``lax.scan``, carrying the (running max, numerator, denominator)
online-softmax state — the published blockwise/flash construction
(Dao et al. 2022, Liu et al. 2023), built TPU-first:

- the per-block update is two batched matmuls (MXU) with an elementwise
  chain between them that XLA fuses; blocks are lane-aligned slabs, so a
  Pallas kernel would only replicate what the scan already gives us
  (measure-first rationale, docs/KERNELS.md);
- compute is uniform across (q, kv) block pairs with masking — no
  data-dependent control flow inside jit; fully-future pairs are
  computed-and-masked, trading ~2x score FLOPs (attention is a small
  slice of hybrid-layer FLOPs) for a branch-free schedule;
- the same block update runs *inside each ring-attention hop*
  (parallel/ring_attention.py), so the sharded path has the identical
  memory profile.

The reference's attention surface lives one dep down
(``mamba_ssm.modules.mha.MHA``, flash-attn CUDA kernels); this is the
TPU-native equivalent for BASELINE config 5 (T=8192 hybrid).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from mamba_distributed_tpu.ops.scan import _divisor_chunk

DEFAULT_BLOCK = 256


def ols_init(b: int, nkv: int, rep: int, tq: int, hd: int):
    """Fresh online-softmax accumulator for a (b, tq, nkv, rep, hd) Q slab."""
    m = jnp.full((b, nkv, rep, tq), -jnp.inf, jnp.float32)
    num = jnp.zeros((b, nkv, rep, tq, hd), jnp.float32)
    den = jnp.zeros((b, nkv, rep, tq), jnp.float32)
    return m, num, den


def ols_block_update(acc, qh, k_blk, v_blk, qpos, kpos):
    """Fold one KV block into the accumulator.

    qh (b, tq, nkv, rep, hd); k_blk/v_blk (b, kb, nkv, hd); qpos (tq,)
    and kpos (kb,) are absolute positions for the causal mask.  All
    softmax math in fp32; the two contractions take
    ``preferred_element_type=f32`` so the MXU accumulates in fp32.
    """
    m, num, den = acc
    hd = qh.shape[-1]
    s = jnp.einsum(
        "bqgrh,bkgh->bgrqk", qh, k_blk, preferred_element_type=jnp.float32
    ) / math.sqrt(hd)
    s = jnp.where((qpos[:, None] >= kpos[None, :])[None, None, None], s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # fully-masked-so-far rows keep m = -inf; exp(-inf - -inf) is guarded
    scale = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    num = num * scale[..., None] + jnp.einsum(
        "bgrqk,bkgh->bgrqh", p.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32,
    )
    den = den * scale + jnp.sum(p, axis=-1)
    return m_new, num, den


def ols_finalize(acc, out_dtype):
    """(b, nkv, rep, tq, hd) accumulator -> (b, tq, nh, hd) output."""
    _, num, den = acc
    out = num / jnp.maximum(den[..., None], 1e-30)
    b, nkv, rep, tq, hd = out.shape
    return jnp.moveaxis(out, 3, 1).reshape(b, tq, nkv * rep, hd).astype(out_dtype)


def blockwise_sdpa_causal(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    offset: int | jax.Array = 0,
    q_block: int = DEFAULT_BLOCK,
    k_block: int = DEFAULT_BLOCK,
) -> jax.Array:
    """Causal softmax(QK^T/sqrt(d))V with GQA broadcast, O(t*block) memory.

    q (b, tq, nh, hd); k/v (b, tk, nkv, hd); ``offset`` = absolute
    position of q[0] minus that of k[0].  Matches the materialized
    fp32-softmax SDPA to fp32 tolerance (tests/test_attention.py).
    """
    b, tq, nh, hd = q.shape
    tk, nkv = k.shape[1], k.shape[2]
    rep = nh // nkv
    qb = _divisor_chunk(tq, q_block)
    kb = _divisor_chunk(tk, k_block)
    nq, nk = tq // qb, tk // kb

    qs = jnp.moveaxis(q.reshape(b, nq, qb, nkv, rep, hd), 1, 0)
    ks = jnp.moveaxis(k.reshape(b, nk, kb, nkv, hd), 1, 0)
    vs = jnp.moveaxis(v.reshape(b, nk, kb, nkv, hd), 1, 0)

    def one_q_block(args):
        qi, q_blk = args
        qpos = offset + qi * qb + jnp.arange(qb)

        def kv_step(acc, inp):
            kj, k_b, v_b = inp
            kpos = kj * kb + jnp.arange(kb)
            return ols_block_update(acc, q_blk, k_b, v_b, qpos, kpos), None

        acc, _ = jax.lax.scan(
            kv_step, ols_init(b, nkv, rep, qb, hd), (jnp.arange(nk), ks, vs)
        )
        return ols_finalize(acc, q.dtype)

    if nq == 1:
        out = one_q_block((jnp.int32(0), qs[0]))[None]
    else:
        out = jax.lax.map(one_q_block, (jnp.arange(nq), qs))
    return jnp.moveaxis(out, 0, 1).reshape(b, tq, nh, hd)
