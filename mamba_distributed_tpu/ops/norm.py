"""RMSNorm family, TPU-native.

Equivalent of the reference dependency's fused Triton layernorm kernels
(``mamba_ssm/ops/triton/layernorm.py`` and ``layernorm_gated.py``, used via
``fused_add_norm=True`` — the MambaConfig default the reference runs with).
On TPU we express the math in plain JAX and let XLA fuse the residual add,
the normalization, and the neighbouring matmul prologue — elementwise
chains like these are exactly what the XLA fusion pass exists for, so a
hand-written Pallas kernel is deliberately not used unless a profile
(scripts/profile_step.py) ever shows the fusion breaking.

Matches the reference semantics: the residual stream is carried in fp32
(``residual_in_fp32=True``), normalization statistics are computed in fp32,
and the output is cast back to the compute dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    """RMSNorm in fp32, output cast back to x.dtype."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(x.dtype)


def add_rms_norm(
    x: jax.Array,
    residual: jax.Array | None,
    weight: jax.Array,
    eps: float = 1e-5,
    residual_dtype: jnp.dtype = jnp.float32,
):
    """Fused residual-add + RMSNorm (prenorm form).

    Computes ``new_residual = x + residual`` (in ``residual_dtype``) and
    returns ``(rms_norm(new_residual), new_residual)`` — the same contract as
    the Triton ``layer_norm_fn(..., prenorm=True)`` path the reference's
    dependency uses between blocks.
    """
    r = x.astype(residual_dtype)
    if residual is not None:
        r = r + residual.astype(residual_dtype)
    return rms_norm(r, weight, eps).astype(x.dtype), r


def rms_norm_gated(
    x: jax.Array,
    z: jax.Array,
    weight: jax.Array,
    eps: float = 1e-5,
    group_size: int | None = None,
) -> jax.Array:
    """Gated RMSNorm: ``rms_norm(x * silu(z))``.

    Equivalent of ``RMSNormGated(norm_before_gate=False)`` used inside the
    Mamba-2 mixer (``mamba_ssm/ops/triton/layernorm_gated.py``).  When
    ``group_size`` is given, statistics are computed per contiguous group
    (grouped RMSNorm, used with ngroups > 1 / tensor parallelism).
    """
    xf = x.astype(jnp.float32) * jax.nn.silu(z.astype(jnp.float32))
    d = xf.shape[-1]
    if group_size is None or group_size == d:
        var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(var + eps)
    else:
        assert d % group_size == 0
        g = d // group_size
        xg = xf.reshape(*xf.shape[:-1], g, group_size)
        var = jnp.mean(jnp.square(xg), axis=-1, keepdims=True)
        y = (xg * jax.lax.rsqrt(var + eps)).reshape(xf.shape)
    return (y * weight.astype(jnp.float32)).astype(x.dtype)
