"""Causal depthwise 1-D convolution, TPU-native.

TPU-native equivalent of the ``causal-conv1d`` CUDA package the reference
depends on (reference requirements.txt:1; ``causal_conv1d/csrc/*.cu`` in
Dao-AILab/causal-conv1d >= 1.4.0): the short (width-4) causal conv inside
every Mamba block, plus the O(1) single-step ``update`` used for recurrent
decode.

For a width-4 depthwise conv, the fastest XLA formulation is a sum of k
shifted elementwise multiply-adds (pure VPU work that XLA fuses into the
surrounding ops) rather than a general conv op.  The ``initial_state``
argument doubles as the decode cache and as the halo received from the
previous shard under sequence parallelism (SURVEY.md section 5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def causal_conv1d(
    x: jax.Array,
    weight: jax.Array,
    bias: jax.Array | None = None,
    activation: str | None = "silu",
    initial_state: jax.Array | None = None,
    return_final_state: bool = False,
    impl: str = "shift",
):
    """Causal depthwise conv over the time axis.

    Args:
      x: (batch, seqlen, dim) input.
      weight: (dim, width) depthwise filter.
      bias: optional (dim,).
      activation: None | "silu".
      initial_state: optional (batch, width-1, dim) — the last ``width-1``
        inputs preceding ``x`` (zeros if None).  Used for decode prefill
        continuation and for sequence-parallel halo exchange.
      return_final_state: if True also return the new (batch, width-1, dim)
        state (the last width-1 columns of the padded input).

    Returns:
      y of shape (batch, seqlen, dim) [, final_state].
    """
    b, t, d = x.shape
    dim, width = weight.shape
    assert dim == d, (dim, d)
    if initial_state is None:
        pad = jnp.zeros((b, width - 1, d), dtype=x.dtype)
    else:
        assert initial_state.shape == (b, width - 1, d), initial_state.shape
        pad = initial_state.astype(x.dtype)
    xp = jnp.concatenate([pad, x], axis=1)  # (b, t + width - 1, d)
    if impl == "xla_conv":
        # grouped conv_general_dilated — XLA's dedicated depthwise path,
        # one op instead of `width` shifted adds.  Sweepable alternative:
        # the round-4 trace showed the shifted-add formulation dragging
        # the activation layout time-minor (pads/copies around the conv).
        # XLA convs are cross-correlations, so tap order matches as-is.
        y = jax.lax.conv_general_dilated(
            xp.astype(jnp.float32),
            weight.astype(jnp.float32)[:, None, :],  # OIW = (d, 1, width)
            window_strides=(1,),
            padding="VALID",
            dimension_numbers=("NWC", "OIW", "NWC"),
            feature_group_count=d,
        )
    elif impl == "shift":
        y = jnp.zeros((b, t, d), dtype=jnp.promote_types(x.dtype, jnp.float32))
        for i in range(width):
            # tap i sees input shifted by (width - 1 - i) steps into the past
            y = y + xp[:, i : i + t, :].astype(y.dtype) * weight[:, i].astype(y.dtype)
    else:
        raise ValueError(f"unsupported conv impl: {impl}")
    if bias is not None:
        y = y + bias.astype(y.dtype)
    if activation == "silu":
        y = jax.nn.silu(y)
    elif activation is not None:
        raise ValueError(f"unsupported activation: {activation}")
    y = y.astype(x.dtype)
    if return_final_state:
        final_state = xp[:, t:, :]  # last width-1 inputs
        return y, final_state
    return y


def causal_conv1d_update(
    x_t: jax.Array,
    conv_state: jax.Array,
    weight: jax.Array,
    bias: jax.Array | None = None,
    activation: str | None = "silu",
):
    """O(1) single-token conv step for recurrent decode.

    Equivalent of ``causal_conv1d_update.cu`` in the reference's dependency.

    Args:
      x_t: (batch, dim) current-token input.
      conv_state: (batch, width-1, dim) previous inputs (oldest first).
      weight: (dim, width); bias: optional (dim,).

    Returns:
      (y_t of shape (batch, dim), new_conv_state).
    """
    b, d = x_t.shape
    dim, width = weight.shape
    assert dim == d
    window = jnp.concatenate([conv_state, x_t[:, None, :]], axis=1)  # (b, width, d)
    y = jnp.einsum("bwd,dw->bd", window.astype(jnp.float32), weight.astype(jnp.float32))
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    if activation == "silu":
        y = jax.nn.silu(y)
    elif activation is not None:
        raise ValueError(f"unsupported activation: {activation}")
    new_state = window[:, 1:, :]
    return y.astype(x_t.dtype), new_state
