"""Selective scan (Mamba-1 SSM recurrence), TPU-native.

Equivalent of the reference dependency's CUDA selective scan
(``mamba_ssm/csrc/selective_scan/`` + ``mamba_ssm/ops/selective_scan_interface.py``
in mamba-ssm 2.2.2, pinned at reference requirements.txt:2) — the kernel the
reference's default ``MambaConfig`` actually executes (SURVEY.md section 2.4).

Recurrence (per batch, channel d, state n):
    h_t = exp(dt_t * A) * h_{t-1} + dt_t * u_t * B_t
    y_t = <C_t, h_t> + D * u_t           (then y *= silu(z) if gated)

Two implementations:
  * ``selective_scan_seq`` — sequential ``lax.scan`` over time; the oracle.
  * ``selective_scan`` — chunked: within a chunk a work-efficient
    ``associative_scan``, between chunks a ``lax.scan`` carry.  The chunk
    body is rematerialized so the backward pass does not store the
    (b, l, d, n) scan intermediates for the whole sequence — this is what
    makes the d_state=16 recurrence fit HBM at T=1024 x 64 layers.

All state math runs in fp32 regardless of input dtype (the CUDA kernel does
the same); inputs/outputs keep the caller's dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _divisor_chunk(t: int, chunk_size: int) -> int:
    """Largest chunk size <= chunk_size that divides t (t is a static shape).

    Warns when the divisor degrades badly (e.g. prime-ish t forces tiny
    chunks): the chunked scans then degenerate toward per-token work.  t is
    static under jit, so the warning fires at trace time, once per shape.
    """
    l = min(chunk_size, t)
    while t % l != 0:
        l -= 1
    if 4 * l <= min(chunk_size, t):
        import warnings

        warnings.warn(
            f"sequence length {t} has no divisor near chunk_size={chunk_size}; "
            f"falling back to chunk size {l}, which degrades the chunked scan "
            f"toward per-token work — pad the sequence to a multiple of a "
            f"reasonable chunk size instead",
            stacklevel=3,
        )
    return l


def _prep(u, delta, A, B, C, D, delta_bias, delta_softplus):
    """Common fp32 promotion + delta preprocessing."""
    uf = u.astype(jnp.float32)
    df = delta.astype(jnp.float32)
    if delta_bias is not None:
        df = df + delta_bias.astype(jnp.float32)
    if delta_softplus:
        df = jax.nn.softplus(df)
    Af = A.astype(jnp.float32)
    Bf = B.astype(jnp.float32)
    Cf = C.astype(jnp.float32)
    Df = None if D is None else D.astype(jnp.float32)
    return uf, df, Af, Bf, Cf, Df


def selective_scan_seq(
    u: jax.Array,
    delta: jax.Array,
    A: jax.Array,
    B: jax.Array,
    C: jax.Array,
    D: jax.Array | None = None,
    z: jax.Array | None = None,
    delta_bias: jax.Array | None = None,
    delta_softplus: bool = False,
    initial_state: jax.Array | None = None,
    return_final_state: bool = False,
):
    """Oracle: plain sequential scan over time.

    Shapes: u/delta (b, t, d); A (d, n); B/C (b, t, n); D (d,); z (b, t, d);
    initial_state (b, d, n).
    """
    b, t, d = u.shape
    n = A.shape[-1]
    uf, df, Af, Bf, Cf, Df = _prep(u, delta, A, B, C, D, delta_bias, delta_softplus)

    h0 = (
        jnp.zeros((b, d, n), jnp.float32)
        if initial_state is None
        else initial_state.astype(jnp.float32)
    )

    def step(h, inputs):
        u_t, dt_t, B_t, C_t = inputs  # (b,d) (b,d) (b,n) (b,n)
        dA = jnp.exp(dt_t[:, :, None] * Af[None])  # (b, d, n)
        dBu = (dt_t * u_t)[:, :, None] * B_t[:, None, :]  # (b, d, n)
        h = h * dA + dBu
        y_t = jnp.einsum("bdn,bn->bd", h, C_t)
        return h, y_t

    xs = (
        jnp.moveaxis(uf, 1, 0),
        jnp.moveaxis(df, 1, 0),
        jnp.moveaxis(Bf, 1, 0),
        jnp.moveaxis(Cf, 1, 0),
    )
    h_last, ys = jax.lax.scan(step, h0, xs)
    y = jnp.moveaxis(ys, 0, 1)  # (b, t, d)
    if Df is not None:
        y = y + uf * Df[None, None, :]
    if z is not None:
        y = y * jax.nn.silu(z.astype(jnp.float32))
    y = y.astype(u.dtype)
    if return_final_state:
        return y, h_last
    return y


def _chunk_scan(h0, u_i, dt_i, Af, B_i, C_i):
    """One chunk: associative scan over the local time axis.

    The (b, l, d, n) intermediates are built *inside* this function so that,
    wrapped in ``jax.checkpoint``, they exist only transiently per chunk in
    both forward and backward.

    h0 (b, d, n); u_i/dt_i (b, l, d); Af (d, n); B_i/C_i (b, l, n).
    Returns (y (b, l, d), h_last (b, d, n)).
    """
    dA = jnp.exp(dt_i[..., None] * Af[None, None])  # (b, l, d, n)
    dBu = (dt_i * u_i)[..., None] * B_i[:, :, None, :]  # (b, l, d, n)

    def combine(left, right):
        a_l, b_l = left
        a_r, b_r = right
        return a_l * a_r, b_l * a_r + b_r

    # fold the carried state into the first element
    dBu = dBu.at[:, 0].add(h0 * dA[:, 0])
    _, h = jax.lax.associative_scan(combine, (dA, dBu), axis=1)
    y = jnp.einsum("bldn,bln->bld", h, C_i)
    return y, h[:, -1]


def selective_scan(
    u: jax.Array,
    delta: jax.Array,
    A: jax.Array,
    B: jax.Array,
    C: jax.Array,
    D: jax.Array | None = None,
    z: jax.Array | None = None,
    delta_bias: jax.Array | None = None,
    delta_softplus: bool = False,
    initial_state: jax.Array | None = None,
    return_final_state: bool = False,
    chunk_size: int = 128,
):
    """Production path: chunked associative scan with rematerialization."""
    b, t, d = u.shape
    n = A.shape[-1]
    uf, df, Af, Bf, Cf, Df = _prep(u, delta, A, B, C, D, delta_bias, delta_softplus)

    h0 = (
        jnp.zeros((b, d, n), jnp.float32)
        if initial_state is None
        else initial_state.astype(jnp.float32)
    )

    l = _divisor_chunk(t, chunk_size)
    nc = t // l

    chunk_body = jax.checkpoint(_chunk_scan)

    def outer(h, inputs):
        u_i, dt_i, B_i, C_i = inputs
        y_i, h = chunk_body(h, u_i, dt_i, Af, B_i, C_i)
        return h, y_i

    xs = (
        jnp.moveaxis(uf.reshape(b, nc, l, d), 1, 0),
        jnp.moveaxis(df.reshape(b, nc, l, d), 1, 0),
        jnp.moveaxis(Bf.reshape(b, nc, l, n), 1, 0),
        jnp.moveaxis(Cf.reshape(b, nc, l, n), 1, 0),
    )
    h_last, ys = jax.lax.scan(outer, h0, xs)
    y = jnp.moveaxis(ys, 0, 1).reshape(b, t, d)

    if Df is not None:
        y = y + uf * Df[None, None, :]
    if z is not None:
        y = y * jax.nn.silu(z.astype(jnp.float32))
    y = y.astype(u.dtype)
    if return_final_state:
        return y, h_last
    return y


def selective_state_update(
    ssm_state: jax.Array,
    x_t: jax.Array,
    dt_t: jax.Array,
    A: jax.Array,
    B_t: jax.Array,
    C_t: jax.Array,
    D: jax.Array | None = None,
    z_t: jax.Array | None = None,
    dt_bias: jax.Array | None = None,
    dt_softplus: bool = True,
):
    """O(1)-per-token recurrent step for decode (Mamba-1 shapes).

    Equivalent of ``mamba_ssm/ops/triton/selective_state_update.py``.

    ssm_state (b, d, n); x_t/dt_t (b, d); A (d, n); B_t/C_t (b, n).
    Returns (y_t (b, d), new_state).
    """
    hf = ssm_state.astype(jnp.float32)
    xf = x_t.astype(jnp.float32)
    dtf = dt_t.astype(jnp.float32)
    if dt_bias is not None:
        dtf = dtf + dt_bias.astype(jnp.float32)
    if dt_softplus:
        dtf = jax.nn.softplus(dtf)
    dA = jnp.exp(dtf[:, :, None] * A.astype(jnp.float32)[None])
    dBu = (dtf * xf)[:, :, None] * B_t.astype(jnp.float32)[:, None, :]
    h = hf * dA + dBu
    y = jnp.einsum("bdn,bn->bd", h, C_t.astype(jnp.float32))
    if D is not None:
        y = y + xf * D.astype(jnp.float32)[None]
    if z_t is not None:
        y = y * jax.nn.silu(z_t.astype(jnp.float32))
    return y.astype(x_t.dtype), h
