"""TPU-native ops: the in-tree equivalents of the reference's CUDA/Triton
kernel dependencies (SURVEY.md section 2.2)."""

from mamba_distributed_tpu.ops.conv import causal_conv1d, causal_conv1d_update
from mamba_distributed_tpu.ops.norm import add_rms_norm, rms_norm, rms_norm_gated
from mamba_distributed_tpu.ops.scan import (
    selective_scan,
    selective_scan_seq,
    selective_state_update,
)
from mamba_distributed_tpu.ops.ssd import (
    chunk_local,
    cumsum_mxu,
    segsum,
    ssd_chunked,
    ssd_seq,
    ssd_state_update,
    state_passing,
)

__all__ = [
    "causal_conv1d",
    "causal_conv1d_update",
    "add_rms_norm",
    "rms_norm",
    "rms_norm_gated",
    "selective_scan",
    "selective_scan_seq",
    "selective_state_update",
    "chunk_local",
    "cumsum_mxu",
    "segsum",
    "ssd_chunked",
    "ssd_seq",
    "ssd_state_update",
    "state_passing",
]
