"""Vocab-blocked cross-entropy: the LM-head matmul + softmax-CE without
ever materializing the (b, t, V) logits tensor.

The dense path (models/lm.py:lm_loss) computes logits once (824 MB bf16
at the 280M recipe, 3.3 GB at the reference's B=32 — reference
train.py:43 recipe) and saves them for the backward.  Here the head
matmul runs block-by-block over the vocab under ``lax.scan`` with an
online logsumexp carry, and the ``custom_vjp`` backward recomputes each
block's logits from the residuals — the activation-memory profile drops
from O(b·t·V) to O(b·t·block).

Numerics match the dense path: each block's logits go through the same
fp32-accumulate → compute-dtype round-trip the dense head performs
(models/lm.py:_final_logits), and the loss is the same
``mean(logsumexp - gathered logit)`` in fp32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _block_logits(normed, head_blk, compute_dtype):
    """One vocab block of the head matmul, with the dense path's dtype
    round-trip (bf16 matmul, fp32 accumulate, compute-dtype output)."""
    out = jnp.dot(
        normed.astype(compute_dtype),
        head_blk.astype(compute_dtype).T,
        preferred_element_type=jnp.float32,
    )
    return out.astype(compute_dtype).astype(jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def blocked_cross_entropy(
    normed: jax.Array,
    head: jax.Array,
    targets: jax.Array,
    n_blocks: int = 8,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """Mean CE over (b, t) positions; ``head`` is (V, d) — the tied
    embedding matrix (models/lm.py tied head) or ``lm_head.kernel.T``."""
    lse, tgt = _forward_scan(normed, head, targets, n_blocks, compute_dtype)
    return jnp.mean(lse - tgt)


def _forward_scan(normed, head, targets, n_blocks, compute_dtype):
    V, d = head.shape
    assert V % n_blocks == 0, (V, n_blocks)
    bs = V // n_blocks
    blocks = head.reshape(n_blocks, bs, d)

    def body(carry, head_blk):
        m, s, tgt, off = carry
        logits = _block_logits(normed, head_blk, compute_dtype)  # (b,t,bs)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[..., None]), axis=-1
        )
        in_blk = (targets >= off) & (targets < off + bs)
        idx = jnp.clip(targets - off, 0, bs - 1)
        tl = jnp.take_along_axis(logits, idx[..., None], axis=-1)[..., 0]
        tgt = jnp.where(in_blk, tl, tgt)
        return (m_new, s, tgt, off + bs), None

    b, t = targets.shape
    init = (
        jnp.full((b, t), -jnp.inf, jnp.float32),
        jnp.zeros((b, t), jnp.float32),
        jnp.zeros((b, t), jnp.float32),
        jnp.zeros((), jnp.int32),
    )
    (m, s, tgt, _), _ = jax.lax.scan(body, init, blocks)
    return m + jnp.log(s), tgt


def _fwd(normed, head, targets, n_blocks, compute_dtype):
    lse, tgt = _forward_scan(normed, head, targets, n_blocks, compute_dtype)
    return jnp.mean(lse - tgt), (normed, head, targets, lse)


def _bwd(n_blocks, compute_dtype, res, g):
    normed, head, targets, lse = res
    V, d = head.shape
    bs = V // n_blocks
    blocks = head.reshape(n_blocks, bs, d)
    b, t = targets.shape
    scale = g / (b * t)  # d(mean)/d(per-position loss)

    def body(carry, head_blk):
        dnormed, off = carry
        logits = _block_logits(normed, head_blk, compute_dtype)
        p = jnp.exp(logits - lse[..., None])  # softmax block, fp32
        in_blk = (targets >= off) & (targets < off + bs)
        idx = jnp.clip(targets - off, 0, bs - 1)
        onehot = (
            jax.nn.one_hot(idx, bs, dtype=jnp.float32)
            * in_blk[..., None]
        )
        dl = ((p - onehot) * scale).astype(compute_dtype)  # (b,t,bs)
        dnormed = dnormed + jnp.einsum(
            "btv,vd->btd", dl, head_blk.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        dblk = jnp.einsum(
            "btv,btd->vd", dl, normed.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        return (dnormed, off + bs), dblk

    init = (jnp.zeros(normed.shape, jnp.float32), jnp.zeros((), jnp.int32))
    (dnormed, _), dhead = jax.lax.scan(body, init, blocks)
    # cast to head.dtype: custom_vjp cotangents must match the primal aval,
    # and head params may one day be held in bf16 (ADVICE r4)
    return (dnormed.astype(normed.dtype),
            dhead.reshape(V, d).astype(head.dtype), None)


blocked_cross_entropy.defvjp(_fwd, _bwd)
