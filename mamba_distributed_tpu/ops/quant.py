"""Symmetric per-channel int8 quantization for the serving path.

Serving decode is weight-bandwidth-bound (docs/SERVING.md): every tick
re-reads the full weights, so halving weight bytes is a direct per-chip
throughput AND capacity multiplier — the same trade the SNIPPETS [2]/[3]
serving stacks make by sharding ``torch.int8`` attention/MLP weights
over their tp/fsdp axes.  Quantization and tensor parallelism compose
here the same way: the quantization (scale) axis of every parameter is
chosen to be its tensor-parallel axis (parallel/sharding._TP_RULES), so
a sharded weight's scales live on the same shard as its channels and no
cross-shard rescale is ever needed:

  * column-parallel kernels (in_proj, wqkv, fc1, lm_head) scale per
    OUTPUT column -> dequant folds into the matmul output:
    ``y = (x @ q) * scale``;
  * row-parallel kernels (out_proj, x_proj, fc2) scale per INPUT row
    -> dequant folds into the activation: ``y = (x * scale) @ q``;
  * the embedding (V, d) scales per VOCAB row — one scale family serves
    both the lookup (``q[ids] * scale[ids]``) and the tied LM head
    (``(x @ q.T) * scale``), and the vocab axis is exactly what
    ``serving_param_specs`` column-parallelizes.

Both forms are exact per-channel dequantization (a diagonal scale
commutes through the contraction), and neither materializes a full-
precision weight copy — XLA fuses the int8->compute cast and the scale
multiply into the dot.

A quantized leaf is a dict ``{"kernel": int8, "scale": f32}`` where the
scale keeps the kernel's rank with every non-channel axis sized 1
(``keepdims``) — ``models/common.linear`` reads the orientation off the
shape (trailing 1 => row scales) and ``parallel/sharding``'s serving
specs shard the scale's channel axis with the kernel's.  The embedding
leaf becomes the same dict shape-for-shape, handled by ``models/lm``'s
embed/tied-head helpers.

What quantizes: exactly the matmul kernels the decode cast
(inference/generate._decode_params) sends to the compute dtype and that
route through ``models/common.linear`` — plus the embedding.  What does
NOT: conv kernels, the MoE router AND expert stacks (w1/w2 run through
their own einsums, not ``linear`` — an fp8/MoE follow-on, ROADMAP),
mamba1's dt_proj (its bias folds into the scan's fp32 delta path and
its matmul bypasses ``linear``), biases, norm scales, and the SSM
scalars — all of whose math stays fp32/bf16 as before.

``assert_stream_close`` is the quantized parity contract's shared
checker (tests/test_quant_serving.py): bf16 serving pins streams
bit-exact; int8 serving pins logit closeness + greedy-token agreement
over the stream, with the PR-2 divergence sentinels counting any
disagreement.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# int8 symmetric range: scales map the per-channel absmax onto +-127
Q_MAX = 127.0
# scale floor: an all-zero channel must not divide by zero (its q rows
# are all zero anyway, so any finite scale round-trips it exactly)
SCALE_EPS = 1e-12

# (path-suffix pattern, channel-axis-from-end) for quantizable kernels
# that ``linear()`` consumes.  -1 = column-parallel (scale per output
# column), -2 = row-parallel (scale per input row) — mirroring
# parallel/sharding._TP_RULES so scales shard with their weights.
_QUANT_RULES: tuple[tuple[tuple[str, ...], int], ...] = (
    (("mixer", "in_proj", "kernel"), -1),
    (("mixer", "out_proj", "kernel"), -2),
    (("mixer", "x_proj", "kernel"), -2),
    (("mixer", "wqkv", "kernel"), -1),
    (("mlp", "fc1", "kernel"), -1),
    (("mlp", "fc2", "kernel"), -2),
    (("lm_head", "kernel"), -1),
)


def quant_axis(names: list[str]) -> int | None:
    """Channel (scale) axis-from-end for a param path, or None when the
    leaf does not quantize.  ``names`` is the tree path as strings."""
    for pattern, ax in _QUANT_RULES:
        k = len(pattern)
        if tuple(names[-k:]) == pattern:
            return ax
    return None


def quantize_channels(w: jax.Array, axis: int) -> dict:
    """Symmetric per-channel int8: scale = absmax/127 along every axis
    EXCEPT ``axis`` (and any leading layer-stack axes are preserved —
    each layer's channels quantize independently because the reduction
    never touches them... it reduces only the one contraction axis for
    2-D-per-layer kernels).

    Concretely: for a kernel of rank r with channel axis ``axis``
    (negative, from the end), the reduction runs over the OTHER of the
    two trailing axes; leading (layer/expert) axes are kept.  Returns
    ``{"kernel": int8, "scale": f32}`` with the scale keeping the
    kernel's rank (reduced axis sized 1) so consumers can read the
    orientation off the shape.
    """
    r = w.ndim
    ax = axis % r
    # the contraction axis is the *other* trailing axis
    red = r - 1 if ax == r - 2 else r - 2
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=red, keepdims=True)
    scale = jnp.maximum(absmax / Q_MAX, SCALE_EPS)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -Q_MAX, Q_MAX)
    return {"kernel": q.astype(jnp.int8), "scale": scale}


def quantize_embedding(emb: jax.Array) -> dict:
    """(V, d) embedding -> per-vocab-row int8: scale (V, 1).  Serves the
    lookup and the tied head with one scale family (module docstring) —
    the same symmetric rule, channel axis 0."""
    return quantize_channels(emb, 0)


def quantize_serving_params(params: dict) -> dict:
    """Quantize a (fp32 master) param tree for serving: every
    ``linear()``-routed kernel named by ``_QUANT_RULES`` becomes
    ``{"kernel": int8, "scale": f32}`` IN PLACE of its dict (bias and
    any other siblings ride along untouched), and the embedding array
    becomes the same dict form.  Everything else — conv, router,
    dt_proj, biases, norms, SSM scalars, MoE experts — passes through
    for the decode cast to handle as before.  Called from
    ``inference/generate._decode_params`` (the ONE shared decode cast)
    when ``cfg.serving_weight_dtype == "int8"``."""

    def walk(tree, names):
        if not isinstance(tree, dict):
            return tree
        out = {}
        for k, v in tree.items():
            if is_quantized(v):
                # idempotent: re-quantizing an already-quantized leaf
                # against its own int8 values would destroy the scales
                out[k] = v
                continue
            if k == "embedding" and not isinstance(v, dict):
                out[k] = quantize_embedding(v)
                continue
            if isinstance(v, dict) and "kernel" in v and not isinstance(
                    v["kernel"], dict):
                ax = quant_axis(list(names) + [k, "kernel"])
                if ax is not None:
                    q = quantize_channels(v["kernel"], ax)
                    out[k] = {**{kk: vv for kk, vv in v.items()
                                 if kk != "kernel"}, **q}
                    continue
            out[k] = walk(v, names + (k,))
        return out

    return walk(params, ())


def apply_dtype_overrides(cfg, weight_dtype: str | None = None,
                          kv_dtype: str | None = None):
    """``dataclasses.replace`` the serving dtype knobs when given — the
    ONE place the bench CLIs' ``--weight-dtype``/``--kv-dtype`` flags
    land (scripts/bench_serving.py, scripts/bench_decode.py), so a
    future knob (the fp8 follow-on) threads through one function."""
    import dataclasses

    kw = {}
    if weight_dtype:
        kw["serving_weight_dtype"] = weight_dtype
    if kv_dtype:
        kw["kv_page_dtype"] = kv_dtype
    return dataclasses.replace(cfg, **kw) if kw else cfg


def dequantize(leaf) -> jax.Array:
    """Materialize a quantized leaf back to f32 (tests / round-trip
    error bounds; the serving hot paths never call this — they fold the
    scale into the matmul instead)."""
    if isinstance(leaf, dict) and "scale" in leaf:
        return leaf["kernel"].astype(jnp.float32) * leaf["scale"]
    return leaf


def is_quantized(leaf) -> bool:
    return isinstance(leaf, dict) and "scale" in leaf and "kernel" in leaf


def param_bytes(params) -> int:
    """Resident bytes of a (possibly quantized) param tree — the
    ``weight_bytes`` gauge serving telemetry stamps when quant is on."""
    return sum(int(x.nbytes) for x in jax.tree.leaves(params))


# --------------------------------------------------------------------- KV
# Per-(page, kv-head) int8 page math shared by the lax fallback and the
# host-side scale planner (models/attention.py); the Pallas kernels
# mirror it in-register (ops/pallas/attention_kernels.py).


def kv_requant(q_old: jax.Array, ratio: jax.Array) -> jax.Array:
    """Re-express old int8 page rows under a new scale: ``round(q_old *
    old_scale/new_scale)``.  ``ratio`` broadcasts over the (page, hd)
    block; scales only grow within a page's life (the update rule keeps
    ``new >= old`` whenever the page has prior content), so the ratio is
    <= 1 and the result stays in range — the clip is a garbage-row
    guard, not a correctness crutch."""
    return jnp.clip(jnp.round(q_old.astype(jnp.float32) * ratio),
                    -Q_MAX, Q_MAX)


def kv_quantize(x: jax.Array, scale: jax.Array) -> jax.Array:
    """Quantize fresh K/V rows under the page's (new) scale."""
    return jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -Q_MAX, Q_MAX)


# ----------------------------------------------------------------- parity


def assert_stream_close(
    got_tokens,
    want_tokens,
    got_logits=None,
    want_logits=None,
    *,
    rtol: float = 2e-2,
    atol: float = 5e-2,
    min_token_agreement: float = 1.0,
    sentinel=None,
    metrics=None,
    label: str = "",
) -> int:
    """The quantized-parity checker: toleranced engine==generate().

    ``got_tokens``/``want_tokens`` are int token streams of equal
    intent (engine slot stream vs solo ``generate()`` suffix).  The
    comparison is prefix-based: once one token differs the tails are
    conditioned on different contexts and comparing them further is
    meaningless, so agreement = matched-prefix length over the compared
    length.  ``min_token_agreement=1.0`` (default) demands exact
    greedy-token agreement — what the int8 path delivers in practice
    because the engine and ``generate()`` run the IDENTICAL quantized
    math — while still reporting any disagreement through the PR-2
    divergence-sentinel machinery instead of an opaque array mismatch:

      * ``sentinel`` (an obs.DivergenceSentinel) gets one
        ``quant_token_disagreement`` flight-recorder event;
      * ``metrics`` (a utils.metrics.ServingMetrics) gets its
        greedy-disagreement counter bumped.

    ``got_logits``/``want_logits`` (optional, aligned to the streams)
    are compared with ``np.allclose(rtol, atol)`` over the MATCHED
    prefix only.  Returns the number of disagreeing tail tokens (0 on
    full agreement).  Bit-exact bf16 streams pass trivially.
    """
    got = np.asarray(got_tokens).reshape(-1)
    want = np.asarray(want_tokens).reshape(-1)
    if got.shape != want.shape:
        raise AssertionError(
            f"stream lengths differ{f' ({label})' if label else ''}: "
            f"{got.shape} vs {want.shape}"
        )
    n = len(got)
    neq = np.nonzero(got != want)[0]
    matched = int(neq[0]) if len(neq) else n
    disagreed = n - matched
    if disagreed:
        if sentinel is not None:
            sentinel.record_event(
                "quant_token_disagreement", label=label,
                first_divergence=matched, compared=n,
                got=int(got[matched]), want=int(want[matched]),
            )
        if metrics is not None:
            metrics.record_greedy_disagreement(disagreed)
    agreement = matched / n if n else 1.0
    if agreement < min_token_agreement:
        raise AssertionError(
            f"token streams diverge at {matched}/{n}"
            f"{f' ({label})' if label else ''}: "
            f"got[{matched}]={got[matched]} want[{matched}]={want[matched]} "
            f"(agreement {agreement:.3f} < {min_token_agreement})"
        )
    if got_logits is not None and want_logits is not None and matched:
        gl = np.asarray(got_logits, np.float32)[:matched]
        wl = np.asarray(want_logits, np.float32)[:matched]
        if not np.allclose(gl, wl, rtol=rtol, atol=atol):
            worst = float(np.max(np.abs(gl - wl)))
            raise AssertionError(
                f"logits diverge beyond tolerance over the matched "
                f"prefix{f' ({label})' if label else ''}: max abs diff "
                f"{worst:.4g} (rtol={rtol}, atol={atol})"
            )
    return disagreed
