"""Token-shard data pipeline (reference: dataloader.py)."""

from mamba_distributed_tpu.data.loader import ShardedTokenLoader
from mamba_distributed_tpu.data.synthetic import ensure_synthetic_shards

__all__ = ["ShardedTokenLoader", "ensure_synthetic_shards"]
