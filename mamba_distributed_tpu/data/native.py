"""ctypes binding + lazy build for the native C++ shard reader.

Builds ``data/native/shard_reader.cc`` once per machine (g++ -O3 -shared)
into a cache directory and exposes ``NativeShard`` — an mmap-backed .npy
token shard with single-pass x/y batch assembly.  ``available()`` gates
callers; everything falls back to the numpy path when the toolchain or the
binding is missing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "native", "shard_reader.cc")
_lib = None
_tried = False


def _build_and_load():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    cache_dir = os.environ.get(
        "MAMBA_TPU_NATIVE_CACHE",
        os.path.join(tempfile.gettempdir(), "mamba_tpu_native"),
    )
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, "shard_reader.so")
    try:
        if not os.path.exists(so_path) or os.path.getmtime(
            so_path
        ) < os.path.getmtime(_SRC):
            # compile to a per-process temp file and rename into place so
            # concurrent builders never dlopen a half-written .so
            tmp_path = f"{so_path}.{os.getpid()}.tmp"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", tmp_path, _SRC],
                check=True, capture_output=True, text=True,
            )
            os.replace(tmp_path, so_path)
        lib = ctypes.CDLL(so_path)
        lib.shard_open.restype = ctypes.c_void_p
        lib.shard_open.argtypes = [ctypes.c_char_p]
        lib.shard_close.argtypes = [ctypes.c_void_p]
        lib.shard_len.restype = ctypes.c_int64
        lib.shard_len.argtypes = [ctypes.c_void_p]
        lib.shard_fill_batch.restype = ctypes.c_int
        lib.shard_fill_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ]
        _lib = lib
    except Exception as e:
        import warnings

        detail = getattr(e, "stderr", "") or str(e)
        warnings.warn(f"native shard reader unavailable: {detail}")
        _lib = None
    return _lib


def available() -> bool:
    return _build_and_load() is not None


class NativeShard:
    """mmap-backed token shard; x/y assembly happens in C++."""

    def __init__(self, path: str):
        lib = _build_and_load()
        if lib is None:
            raise RuntimeError("native shard reader unavailable")
        self._lib = lib
        self._handle = lib.shard_open(path.encode())
        if not self._handle:
            raise OSError(f"cannot open npy shard: {path}")
        self.path = path

    def __len__(self) -> int:
        return int(self._lib.shard_len(self._handle))

    def fill_batch(self, pos: int, B: int, T: int):
        """tokens[pos : pos+B*T(+1)] -> x, y of shape (B, T) int32."""
        x = np.empty(B * T, np.int32)
        y = np.empty(B * T, np.int32)
        rc = self._lib.shard_fill_batch(
            self._handle, pos, B * T,
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if rc != 0:
            raise IndexError(
                f"batch window [{pos}, {pos + B * T + 1}) out of range "
                f"for shard of {len(self)} tokens"
            )
        return x.reshape(B, T), y.reshape(B, T)

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.shard_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
