"""Zero-egress GPT-2 byte-level BPE tokenizer.

The reference tokenizes with tiktoken's "gpt2" encoding fetched at
runtime (/root/reference/model.py:20, train.py:41, eval.py:26) — a
network download this environment cannot make.  The *algorithm* (byte →
unicode table, regex pre-split, ranked-merge BPE) is vendored here in
full; the *data* is the standard OpenAI release pair every GPT-2
distribution ships (~1MB total), loaded from a local directory:

    <dir>/encoder.json   token -> id map (50257 entries incl. <|endoftext|>)
    <dir>/vocab.bpe      ranked merges, one pair per line (version header)

HF checkpoints carry the same data as ``vocab.json``/``merges.txt``;
both filename conventions are accepted.  Point ``GPT2_BPE_DIR`` (or the
``bpe_dir`` argument) at the directory and ``eval.py`` /
``train.py --sample-prompt`` run fully offline; without the files the
CLIs fall back to tiktoken (if it can load) and then fail with a clear
message, and the library APIs keep accepting injected ``encode``
callables as before.

Encoding matches tiktoken's "gpt2" exactly: same pre-split regex, same
byte encoder, same merge ranks — pinned by tests/test_gpt2_bpe.py with a
synthetic merge table (the real data files are not redistributable into
this environment, but the algorithm is data-independent).

The merge loop runs natively when a toolchain is present: the id-level
C++ kernel (data/native/bpe_merge.cc, the counterpart of tiktoken's
Rust core) is lazily built by data/native_bpe.py and differentially
tested against the pure-Python loop; ``MDT_NATIVE_BPE=0`` forces the
Python path.
"""

from __future__ import annotations

import functools
import json
import os

import regex  # full \p{L}/\p{N} support (transformers dependency)

ENDOFTEXT = "<|endoftext|>"
ENDOFTEXT_ID = 50256

# GPT-2's pre-tokenization pattern (contractions, letter runs, number
# runs, punctuation runs, trailing-space handling)
_PAT = regex.compile(
    r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""
)


@functools.lru_cache(maxsize=1)
def bytes_to_unicode() -> dict[int, str]:
    """The reversible byte -> printable-unicode table byte-level BPE uses.

    Printable ASCII + two latin-1 ranges map to themselves; the remaining
    68 bytes map to 256+offset codepoints so every byte has a visible,
    non-whitespace character and merge files stay plain text.
    """
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


def _find_file(bpe_dir: str, names: tuple[str, ...]) -> str | None:
    for name in names:
        p = os.path.join(bpe_dir, name)
        if os.path.exists(p):
            return p
    return None


class GPT2BPE:
    """Byte-level BPE with GPT-2 semantics over a loaded vocab."""

    def __init__(self, encoder: dict[str, int], merges: list[tuple[str, str]]):
        self.encoder = encoder
        self.decoder = {v: k for k, v in encoder.items()}
        self.ranks = {pair: i for i, pair in enumerate(merges)}
        self.byte_enc = bytes_to_unicode()
        self.byte_dec = {v: k for k, v in self.byte_enc.items()}
        # the only cache is id-level, keyed by pre-token; _bpe itself is
        # uncached (it runs at most once per distinct pre-token)
        self._id_cache: dict[str, tuple[int, ...]] = {}
        self._native = None
        self._native_tried = False

    def _native_table(self):
        """Lazy id-level merge table on the C++ merge loop (data/native_bpe.py);
        None when the toolchain is absent or the vocab is degenerate."""
        if self._native_tried:
            return self._native
        self._native_tried = True
        try:
            from mamba_distributed_tpu.data.native_bpe import (
                NativeBpeTable,
                available,
            )

            if not available():
                return None
            triples = []
            for (sa, sb), _rank in sorted(
                self.ranks.items(), key=lambda kv: kv[1]
            ):
                a, b = self.encoder.get(sa), self.encoder.get(sb)
                c = self.encoder.get(sa + sb)
                if a is None or b is None or c is None:
                    return None  # vocab/merge mismatch: stay on Python path
                triples.append((a, b, c))
            # id-level BPE needs every single-byte symbol to have an id
            if any(s not in self.encoder for s in self.byte_enc.values()):
                return None
            # raw byte -> id, skipping the unicode-symbol detour entirely
            self._byte_ids = [
                self.encoder[self.byte_enc[b]] for b in range(256)
            ]
            self._native = NativeBpeTable(triples)
        except Exception:
            self._native = None
        return self._native

    @classmethod
    def from_dir(cls, bpe_dir: str) -> "GPT2BPE":
        enc_path = _find_file(bpe_dir, ("encoder.json", "vocab.json"))
        bpe_path = _find_file(bpe_dir, ("vocab.bpe", "merges.txt"))
        if enc_path is None or bpe_path is None:
            raise FileNotFoundError(
                f"GPT-2 BPE data not found in {bpe_dir!r}: need "
                "encoder.json (or vocab.json) + vocab.bpe (or merges.txt); "
                "copy them from any GPT-2 distribution (module docstring)."
            )
        with open(enc_path, encoding="utf-8") as f:
            encoder = json.load(f)
        with open(bpe_path, encoding="utf-8") as f:
            lines = f.read().split("\n")
        # the standard first-line "#version: ..." header is metadata, not a
        # merge (a real merge CAN start with '#', so only line 0 is special)
        if lines and lines[0].startswith("#version"):
            lines = lines[1:]
        merges = []
        for line in lines:
            parts = line.split()
            if len(parts) == 2:
                merges.append((parts[0], parts[1]))
            # blank / malformed lines are skipped
        return cls(encoder, merges)

    def _bpe(self, token: str) -> tuple[str, ...]:
        word = tuple(token)
        while len(word) > 1:
            pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
            best = min(pairs, key=lambda p: self.ranks.get(p, float("inf")))
            if best not in self.ranks:
                break
            first, second = best
            merged = []
            i = 0
            while i < len(word):
                if (
                    i < len(word) - 1
                    and word[i] == first
                    and word[i + 1] == second
                ):
                    merged.append(first + second)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = tuple(merged)
        return word

    def encode(self, text: str) -> list[int]:
        native = self._native_table()
        toks = _PAT.findall(text)
        cache = self._id_cache
        if native is not None:
            # batch every cache miss of this call into ONE native call
            misses = {t for t in toks if t not in cache}
            if misses:
                misses = list(misses)
                flat: list[int] = []
                offsets = [0]
                byte_ids = self._byte_ids
                for t in misses:
                    flat.extend(byte_ids[b] for b in t.encode("utf-8"))
                    offsets.append(len(flat))
                lens, merged = native.apply_spans(flat, offsets)
                pos = 0
                for t, ln in zip(misses, lens):
                    cache[t] = tuple(merged[pos : pos + ln])
                    pos += ln
        ids: list[int] = []
        for tok in toks:
            cached = cache.get(tok)
            if cached is None:  # pure-Python path (no native table)
                mapped = "".join(self.byte_enc[b] for b in tok.encode("utf-8"))
                cached = tuple(
                    self.encoder[piece] for piece in self._bpe(mapped)
                )
                cache[tok] = cached
            ids.extend(cached)
        return ids

    def decode(self, ids) -> str:
        # ids outside the vocab (e.g. the 50257..50303 padding range a
        # model's padded head can emit) render as U+FFFD instead of raising
        text = "".join(self.decoder.get(int(i), "�") for i in ids)
        data = bytearray()
        for c in text:
            b = self.byte_dec.get(c)
            if b is None:
                data.extend("�".encode("utf-8"))
            else:
                data.append(b)
        return data.decode("utf-8", errors="replace")


def load_encoder(bpe_dir: str | None = None):
    """Best-effort zero-egress (encode, decode) pair.

    Order: local BPE files (GPT2_BPE_DIR, default ./gpt2_bpe) -> tiktoken
    (works only with a warm cache or network) -> raises with instructions.
    """
    bpe_dir = bpe_dir or os.environ.get("GPT2_BPE_DIR", "gpt2_bpe")
    local_err = None
    if os.path.isdir(bpe_dir):
        try:
            bpe = GPT2BPE.from_dir(bpe_dir)
            return bpe.encode, bpe.decode
        except FileNotFoundError as e:
            # dir exists but lacks the data files — still try tiktoken
            # (the promised fallback) before giving up
            local_err = e
    try:
        import tiktoken

        enc = tiktoken.get_encoding("gpt2")
        return enc.encode, enc.decode
    except Exception as e:
        raise FileNotFoundError(
            f"no GPT-2 BPE available: local dir {bpe_dir!r} "
            f"{'incomplete (' + str(local_err) + ')' if local_err else 'absent'} "
            f"and tiktoken failed ({type(e).__name__}: {e}). Drop "
            "encoder.json/vocab.bpe (or vocab.json/merges.txt) into "
            f"{bpe_dir!r} — see mamba_distributed_tpu/data/gpt2_bpe.py."
        )
