"""ctypes binding + lazy build for the native BPE merge loop.

Same pattern as data/native.py (the shard reader): build
``data/native/bpe_merge.cc`` once per machine into a cache dir, gate on
``available()``, fall back to the pure-Python merge when the toolchain
is missing or ``MDT_NATIVE_BPE=0``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

_SRC = os.path.join(os.path.dirname(__file__), "native", "bpe_merge.cc")
_lib = None
_tried = False


def _build_and_load():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("MDT_NATIVE_BPE") == "0":
        return None
    cache_dir = os.environ.get(
        "MAMBA_TPU_NATIVE_CACHE",
        os.path.join(tempfile.gettempdir(), "mamba_tpu_native"),
    )
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, "bpe_merge.so")
    try:
        if not os.path.exists(so_path) or os.path.getmtime(
            so_path
        ) < os.path.getmtime(_SRC):
            tmp_path = f"{so_path}.{os.getpid()}.tmp"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", tmp_path, _SRC],
                check=True, capture_output=True, text=True,
            )
            os.replace(tmp_path, so_path)
        lib = ctypes.CDLL(so_path)
        lib.bpe_table_new.restype = ctypes.c_void_p
        lib.bpe_table_new.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ]
        lib.bpe_table_free.argtypes = [ctypes.c_void_p]
        lib.bpe_apply.restype = ctypes.c_int32
        lib.bpe_apply.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ]
        lib.bpe_apply_spans.restype = ctypes.c_int32
        lib.bpe_apply_spans.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        _lib = lib
    except Exception as e:
        import warnings

        detail = getattr(e, "stderr", "") or str(e)
        warnings.warn(f"native BPE unavailable: {detail}")
        _lib = None
    return _lib


def available() -> bool:
    return _build_and_load() is not None


class NativeBpeTable:
    """Owns a C-side (a, b) -> (rank, merged) table."""

    def __init__(self, triples: list[tuple[int, int, int]]):
        lib = _build_and_load()
        if lib is None:
            raise RuntimeError("native BPE unavailable")
        self._lib = lib
        n = len(triples)
        Arr = ctypes.c_int32 * n
        a = Arr(*(t[0] for t in triples))
        b = Arr(*(t[1] for t in triples))
        c = Arr(*(t[2] for t in triples))
        self._handle = lib.bpe_table_new(a, b, c, n)

    def apply(self, ids: list[int]) -> list[int]:
        n = len(ids)
        buf = (ctypes.c_int32 * n)(*ids)
        out_n = self._lib.bpe_apply(self._handle, buf, n)
        return buf[:out_n]

    def apply_spans(self, flat: list[int], offsets: list[int]):
        """Merge many concatenated spans in ONE native call.

        flat = span0 + span1 + ...; offsets has len(spans)+1 entries.
        Returns (per-span merged lengths, compacted merged ids).
        """
        n_spans = len(offsets) - 1
        buf = (ctypes.c_int32 * len(flat))(*flat)
        offs = (ctypes.c_int32 * len(offsets))(*offsets)
        lens = (ctypes.c_int32 * n_spans)()
        total = self._lib.bpe_apply_spans(self._handle, buf, offs, n_spans, lens)
        return lens[:n_spans], buf[:total]

    def __del__(self):
        lib = getattr(self, "_lib", None)
        handle = getattr(self, "_handle", None)
        if lib is not None and handle:
            lib.bpe_table_free(handle)
