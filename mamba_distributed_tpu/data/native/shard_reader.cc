// Native shard reader: mmap'd .npy token shards + batch assembly.
//
// The runtime-native piece of the data pipeline (the reference's loader,
// /root/reference/dataloader.py:7-11, np.load()s the whole shard into host
// RAM and re-slices tensors per batch).  Here shards are memory-mapped —
// the OS pages in only the strided windows a rank actually reads, which is
// what multi-host rank striding wants — and the x/y next-token pair is
// assembled into caller-provided int32 buffers in one pass.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image); built
// lazily by data/native.py with g++ -O3 -shared -fPIC.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct NpyShard {
  void* map = nullptr;       // whole file mapping
  size_t map_len = 0;
  const uint8_t* data = nullptr;  // token payload (after the npy header)
  int64_t n_tokens = 0;
  int dtype_size = 0;        // 2 (uint16) or 4 (uint32/int32)
  bool is_signed = false;
};

// Parse the .npy v1/v2 header; returns payload offset or -1.
// Header format: \x93NUMPY <maj> <min> <hlen:2 or 4> <dict padded to 64>
int64_t parse_npy_header(const uint8_t* buf, size_t len, int* dtype_size,
                         bool* is_signed, int64_t* count) {
  if (len < 10 || memcmp(buf, "\x93NUMPY", 6) != 0) return -1;
  int major = buf[6];
  size_t hlen, off;
  if (major == 1) {
    hlen = buf[8] | (buf[9] << 8);
    off = 10;
  } else {
    hlen = buf[8] | (buf[9] << 8) | (static_cast<size_t>(buf[10]) << 16) |
           (static_cast<size_t>(buf[11]) << 24);
    off = 12;
  }
  if (off + hlen > len) return -1;
  char header[4096];
  size_t n = hlen < sizeof(header) - 1 ? hlen : sizeof(header) - 1;
  memcpy(header, buf + off, n);
  header[n] = 0;

  // descr: expect little-endian or native 2/4-byte ints
  const char* descr = strstr(header, "'descr'");
  if (!descr) return -1;
  const char* q = strchr(descr + 7, '\'');
  if (!q) return -1;
  const char* type_str = q + 1;  // e.g. "<u2", "<u4", "<i4", "|u1"
  char endian = type_str[0];
  char kind = type_str[1];
  int size = atoi(type_str + 2);
  if (endian == '>') return -1;  // big-endian unsupported
  if (kind != 'u' && kind != 'i') return -1;
  if (size != 2 && size != 4) return -1;
  *dtype_size = size;
  *is_signed = (kind == 'i');

  if (strstr(header, "'fortran_order': True")) return -1;

  const char* shape = strstr(header, "'shape'");
  if (!shape) return -1;
  const char* paren = strchr(shape, '(');
  if (!paren) return -1;
  int64_t total = 1;
  const char* pc = paren + 1;
  while (*pc && *pc != ')') {
    if (*pc >= '0' && *pc <= '9') {
      total *= strtoll(pc, const_cast<char**>(&pc), 10);
    } else {
      ++pc;
    }
  }
  *count = total;
  return static_cast<int64_t>(off + hlen);
}

}  // namespace

extern "C" {

NpyShard* shard_open(const char* path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 16) {
    close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (map == MAP_FAILED) return nullptr;

  int dtype_size = 0;
  bool is_signed = false;
  int64_t count = 0;
  int64_t payload = parse_npy_header(static_cast<const uint8_t*>(map),
                                     st.st_size, &dtype_size, &is_signed,
                                     &count);
  if (payload < 0 ||
      payload + count * static_cast<int64_t>(dtype_size) >
          static_cast<int64_t>(st.st_size)) {
    munmap(map, st.st_size);
    return nullptr;
  }
  NpyShard* s = new NpyShard();
  s->map = map;
  s->map_len = st.st_size;
  s->data = static_cast<const uint8_t*>(map) + payload;
  s->n_tokens = count;
  s->dtype_size = dtype_size;
  s->is_signed = is_signed;
  // rank-strided access: suppress full-file readahead so each rank only
  // pages in the windows it actually reads
  madvise(map, st.st_size, MADV_RANDOM);
  return s;
}

void shard_close(NpyShard* s) {
  if (!s) return;
  munmap(s->map, s->map_len);
  delete s;
}

int64_t shard_len(const NpyShard* s) { return s ? s->n_tokens : -1; }

// Fill x = tokens[pos : pos+count], y = tokens[pos+1 : pos+count+1] as int32.
// Returns 0 on success, -1 on out-of-range.
int shard_fill_batch(const NpyShard* s, int64_t pos, int64_t count,
                     int32_t* x, int32_t* y) {
  if (!s || pos < 0 || pos + count + 1 > s->n_tokens) return -1;
  if (s->dtype_size == 2) {
    const uint16_t* p = reinterpret_cast<const uint16_t*>(s->data) + pos;
    for (int64_t i = 0; i < count; ++i) {
      x[i] = static_cast<int32_t>(p[i]);
      y[i] = static_cast<int32_t>(p[i + 1]);
    }
  } else if (s->is_signed) {
    const int32_t* p = reinterpret_cast<const int32_t*>(s->data) + pos;
    memcpy(x, p, count * sizeof(int32_t));
    memcpy(y, p + 1, count * sizeof(int32_t));
  } else {
    const uint32_t* p = reinterpret_cast<const uint32_t*>(s->data) + pos;
    for (int64_t i = 0; i < count; ++i) {
      x[i] = static_cast<int32_t>(p[i]);
      y[i] = static_cast<int32_t>(p[i + 1]);
    }
  }
  return 0;
}

}  // extern "C"
