// Native BPE merge loop for the vendored GPT-2 tokenizer.
//
// The reference's tokenizer dependency (tiktoken) does its merge loop in
// Rust; this is the C++ counterpart for the zero-egress BPE
// (data/gpt2_bpe.py), used by scripts/prepare_data.py where tokenization
// is the whole job.  Merges are applied on vocab *ids* — Python
// precomputes (a, b) -> (rank, merged) triples from encoder.json +
// vocab.bpe, so no strings cross the boundary.
//
// Semantics mirror GPT2BPE._bpe exactly: repeatedly find the
// lowest-rank adjacent pair present in the table, then merge ALL its
// left-to-right non-overlapping occurrences; stop when no pair ranks.
//
// Built lazily by data/native_bpe.py (g++ -O3 -shared), ctypes ABI.

#include <cstddef>
#include <cstdint>
#include <unordered_map>
#include <utility>

namespace {
struct BpeTable {
  std::unordered_map<uint64_t, std::pair<int32_t, int32_t>> m;  // (rank, merged)
};
inline uint64_t pack(int32_t a, int32_t b) {
  return (uint64_t(uint32_t(a)) << 32) | uint32_t(b);
}
}  // namespace

extern "C" {

void* bpe_table_new(const int32_t* a, const int32_t* b, const int32_t* merged,
                    int32_t n) {
  auto* t = new BpeTable();
  t->m.reserve(std::size_t(n) * 2);
  for (int32_t i = 0; i < n; ++i) {
    // first occurrence wins, matching dict-of-ranks construction order
    t->m.emplace(pack(a[i], b[i]), std::make_pair(i, merged[i]));
  }
  return t;
}

void bpe_table_free(void* h) { delete static_cast<BpeTable*>(h); }

// In-place BPE over tok[0..n); returns the merged length.
int32_t bpe_apply(void* h, int32_t* tok, int32_t n) {
  const auto& m = static_cast<BpeTable*>(h)->m;
  while (n > 1) {
    int32_t best_rank = INT32_MAX, best_merged = -1, best_a = 0, best_b = 0;
    for (int32_t i = 0; i + 1 < n; ++i) {
      auto it = m.find(pack(tok[i], tok[i + 1]));
      if (it != m.end() && it->second.first < best_rank) {
        best_rank = it->second.first;
        best_merged = it->second.second;
        best_a = tok[i];
        best_b = tok[i + 1];
      }
    }
    if (best_merged < 0) break;
    int32_t w = 0;
    for (int32_t i = 0; i < n;) {
      if (i + 1 < n && tok[i] == best_a && tok[i + 1] == best_b) {
        tok[w++] = best_merged;
        i += 2;
      } else {
        tok[w++] = tok[i++];
      }
    }
    n = w;
  }
  return n;
}

// Batched form: tok holds n_spans concatenated spans, span i occupying
// tok[offsets[i] .. offsets[i+1]).  Each span is merged independently and
// the results are compacted to the front of tok (w never catches up to
// the next unprocessed span since merging only shrinks).  Per-span merged
// lengths land in out_lens; returns the total compacted length.  One
// ctypes call per document instead of per pre-token.
int32_t bpe_apply_spans(void* h, int32_t* tok, const int32_t* offsets,
                        int32_t n_spans, int32_t* out_lens) {
  int32_t w = 0;
  for (int32_t i = 0; i < n_spans; ++i) {
    int32_t s = offsets[i];
    int32_t n = bpe_apply(h, tok + s, offsets[i + 1] - s);
    out_lens[i] = n;
    for (int32_t j = 0; j < n; ++j) tok[w++] = tok[s + j];
  }
  return w;
}

}  // extern "C"
