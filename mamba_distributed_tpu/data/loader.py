"""Rank-strided `.npy` token-shard loader.

Reproduces the reference ``DataLoaderLite`` semantics
(/root/reference/dataloader.py:14-52): sorted shard discovery filtered by
split name, rank-strided sequential windows (rank r reads windows
r, r+W, r+2W, ... of each shard), next-token (x, y) pairs from a B*T+1
slice, shard cycling with dropped tails, deterministic order, no shuffling.

Beyond the reference it adds (SURVEY.md §5 checkpoint/resume):
  * ``state()`` / ``restore()`` — exact-resume loader position for
    checkpointing (the reference cannot resume, train.py:161-162);
  * multi-host awareness — on TPU-VM pods each host is one "process", so
    ``process_rank``/``num_processes`` default to the JAX process grid;
  * numpy outputs shaped (B, T) ready to be device_put against a
    data-sharded ``NamedSharding``.
"""

from __future__ import annotations

import os

import numpy as np


def load_tokens(path: str) -> np.ndarray:
    """np.load + widen to int32 (shards are uint16/uint32 on disk)."""
    arr = np.load(path)
    return arr.astype(np.int32)


class ShardedTokenLoader:
    def __init__(
        self,
        B: int,
        T: int,
        data_dir: str,
        split: str = "train",
        process_rank: int = 0,
        num_processes: int = 1,
        master_process: bool = True,
    ):
        assert split in {"train", "val"}
        self.B, self.T = B, T
        self.process_rank = process_rank
        self.num_processes = num_processes

        shards = sorted(
            os.path.join(data_dir, s)
            for s in os.listdir(data_dir)
            if split in s and s.endswith(".npy")
        )
        assert shards, f"no shards found for split {split} in {data_dir}"
        self.shards = shards
        if master_process:
            print(f"found {len(shards)} shards for split {split}")
        self.reset()

    def reset(self) -> None:
        self.current_shard = 0
        self.tokens = load_tokens(self.shards[self.current_shard])
        self.current_position = self.B * self.T * self.process_rank

    def next_batch(self) -> tuple[np.ndarray, np.ndarray]:
        B, T = self.B, self.T
        buf = self.tokens[self.current_position : self.current_position + B * T + 1]
        x = buf[:-1].reshape(B, T)
        y = buf[1:].reshape(B, T)
        self.current_position += B * T * self.num_processes
        # advance when the *next* strided window would overrun the shard
        # (same guard as reference dataloader.py:46-51 — tails are dropped)
        if self.current_position + (B * T * self.num_processes + 1) > len(self.tokens):
            self.current_shard = (self.current_shard + 1) % len(self.shards)
            self.tokens = load_tokens(self.shards[self.current_shard])
            self.current_position = B * T * self.process_rank
        return x, y

    # --- exact-resume support (absent from the reference) ---

    def state(self) -> dict:
        return {
            "current_shard": self.current_shard,
            "current_position": self.current_position,
        }

    def restore(self, state: dict) -> None:
        self.current_shard = int(state["current_shard"]) % len(self.shards)
        self.tokens = load_tokens(self.shards[self.current_shard])
        self.current_position = int(state["current_position"])
