"""Rank-strided `.npy` token-shard loader.

Reproduces the reference ``DataLoaderLite`` semantics
(/root/reference/dataloader.py:14-52): sorted shard discovery filtered by
split name, rank-strided sequential windows (rank r reads windows
r, r+W, r+2W, ... of each shard), next-token (x, y) pairs from a B*T+1
slice, shard cycling with dropped tails, deterministic order, no shuffling.

Beyond the reference it adds:
  * ``state()`` / ``restore()`` — exact-resume loader position for
    checkpointing (the reference cannot resume, train.py:161-162);
  * multi-host awareness — on TPU-VM pods each host is one "process", so
    ``process_rank``/``num_processes`` default to the JAX process grid;
  * a native C++ backend (data/native.py + data/native/shard_reader.cc):
    shards are memory-mapped instead of fully loaded into host RAM
    (the reference np.load()s the whole shard, dataloader.py:7-11), and
    the x/y pair is assembled in one C++ pass.  ``backend="auto"`` uses
    it when the toolchain built it; numpy otherwise.  Both backends are
    tested to produce identical batches.
  * single-batch prefetch — a worker thread assembles the next batch
    while the caller trains on the current one.  The batch *sequence* is
    a pure function of the cursor (shard index, position), so prefetching
    changes nothing observable: ``state()`` still reports the next
    unconsumed cursor and resume is bit-identical (tests pin this).
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np


def load_tokens(path: str) -> np.ndarray:
    """np.load + widen to int32 (shards are uint16/uint32 on disk)."""
    arr = np.load(path)
    return arr.astype(np.int32)


class ShardedTokenLoader:
    def __init__(
        self,
        B: int,
        T: int,
        data_dir: str,
        split: str = "train",
        process_rank: int = 0,
        num_processes: int = 1,
        master_process: bool = True,
        backend: str = "auto",
        prefetch: bool = True,
    ):
        assert split in {"train", "val"}
        assert backend in {"auto", "native", "numpy"}
        self.B, self.T = B, T
        self.process_rank = process_rank
        self.num_processes = num_processes

        self._backend = backend
        if backend == "numpy":
            self._native = False
        else:
            from mamba_distributed_tpu.data import native

            self._native = native.available()
            if backend == "native" and not self._native:
                raise RuntimeError("native shard reader unavailable")

        shards = sorted(
            os.path.join(data_dir, s)
            for s in os.listdir(data_dir)
            if split in s and s.endswith(".npy")
        )
        assert shards, f"no shards found for split {split} in {data_dir}"
        self.shards = shards
        if master_process:
            backend_name = "native" if self._native else "numpy"
            print(f"found {len(shards)} shards for split {split} ({backend_name})")

        self._open_idx: int | None = None
        self._shard = None
        self._pool = ThreadPoolExecutor(max_workers=1) if prefetch else None
        self._pending = None  # (cursor, Future) for the batch at that cursor
        self.reset()
        # open shard 0 eagerly: backend="native" fails loudly at construction
        # on unsupported shards, and "auto" settles its backend up front
        self._open_shard(0)

    # --- shard backends ---

    def _open_shard(self, idx: int) -> None:
        if idx == self._open_idx:
            return
        path = self.shards[idx]
        if self._native:
            from mamba_distributed_tpu.data.native import NativeShard

            if self._shard is not None:
                self._shard.close()
            try:
                self._shard = NativeShard(path)
            except OSError:
                if self._backend == "native":
                    raise
                # "auto": shard dtype/layout outside the C++ parser's set
                # (e.g. int64, big-endian) — degrade to numpy for this loader
                self._shard = None
                self._native = False
            else:
                self._shard_len = len(self._shard)
                self._open_idx = idx
                return
        self._shard = None
        self.tokens = load_tokens(path)
        self._shard_len = len(self.tokens)
        self._open_idx = idx

    def _slice(self, pos: int):
        B, T = self.B, self.T
        if self._native:
            return self._shard.fill_batch(pos, B, T)
        buf = self.tokens[pos : pos + B * T + 1]
        return buf[:-1].reshape(B, T), buf[1:].reshape(B, T)

    def _compute(self, cursor):
        """Pure step: cursor (shard, pos) -> ((x, y), next_cursor).

        Only ever runs on the worker thread (or inline when prefetch is
        off / missed), never concurrently with itself — max_workers=1 and
        the consume-then-resubmit protocol guarantee that.
        """
        shard_idx, pos = cursor
        B, T = self.B, self.T
        self._open_shard(shard_idx)
        x, y = self._slice(pos)
        next_pos = pos + B * T * self.num_processes
        # advance when the *next* strided window would overrun the shard
        # (same guard as reference dataloader.py:46-51 — tails are dropped)
        if next_pos + (B * T * self.num_processes + 1) > self._shard_len:
            shard_idx = (shard_idx + 1) % len(self.shards)
            next_pos = B * T * self.process_rank
        return (x, y), (shard_idx, next_pos)

    # --- reference API ---

    def reset(self) -> None:
        self._cancel_pending()
        self._cursor = (0, self.B * self.T * self.process_rank)

    def next_batch(self) -> tuple[np.ndarray, np.ndarray]:
        if self._pending is not None and self._pending[0] == self._cursor:
            fut = self._pending[1]
            # clear BEFORE result(): if the worker raised (e.g. transient
            # I/O), the exception propagates once and the next call retries
            # inline instead of re-raising the cached failure forever
            self._pending = None
            (x, y), self._cursor = fut.result()
        else:
            self._cancel_pending()
            (x, y), self._cursor = self._compute(self._cursor)
        if self._pool is not None:
            cur = self._cursor
            self._pending = (cur, self._pool.submit(self._compute, cur))
        return x, y

    def _cancel_pending(self) -> None:
        if getattr(self, "_pending", None) is not None:
            fut = self._pending[1]
            # usually the prefetch hasn't started yet — cancel() skips the
            # wasted shard read; if it IS mid-_compute, wait it out so shard
            # state is quiescent before we move the cursor under it
            if not fut.cancel():
                try:
                    fut.result()
                except Exception as e:
                    # discarded on purpose (the cursor is being moved), but
                    # a persistent shard I/O failure should be visible HERE,
                    # not one batch later via the inline retry
                    import warnings

                    warnings.warn(
                        f"discarding failed prefetch during reset: {e!r}",
                        RuntimeWarning,
                        stacklevel=3,
                    )
            self._pending = None

    def close(self) -> None:
        """Stop the prefetch worker (joins any in-flight compute)."""
        self._cancel_pending()
        if self._pool is not None:
            self._pool.shutdown(wait=True, cancel_futures=True)
            self._pool = None
        if self._shard is not None:
            self._shard.close()
            self._shard = None
        self.tokens = None  # numpy backend holds the whole shard in RAM
        self._open_idx = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # --- exact-resume support (absent from the reference) ---

    @property
    def current_shard(self) -> int:
        return self._cursor[0]

    @property
    def current_position(self) -> int:
        return self._cursor[1]

    def state(self) -> dict:
        return {
            "current_shard": self._cursor[0],
            "current_position": self._cursor[1],
        }

    def restore(self, state: dict) -> None:
        self._cancel_pending()
        self._cursor = (
            int(state["current_shard"]) % len(self.shards),
            int(state["current_position"]),
        )
