"""Rank-strided `.npy` token-shard loader.

Reproduces the reference ``DataLoaderLite`` semantics
(/root/reference/dataloader.py:14-52): sorted shard discovery filtered by
split name, rank-strided sequential windows (rank r reads windows
r, r+W, r+2W, ... of each shard), next-token (x, y) pairs from a B*T+1
slice, shard cycling with dropped tails, deterministic order, no shuffling.

Beyond the reference it adds:
  * ``state()`` / ``restore()`` — exact-resume loader position for
    checkpointing (the reference cannot resume, train.py:161-162);
  * multi-host awareness — on TPU-VM pods each host is one "process", so
    ``process_rank``/``num_processes`` default to the JAX process grid;
  * a native C++ backend (data/native.py + data/native/shard_reader.cc):
    shards are memory-mapped instead of fully loaded into host RAM
    (the reference np.load()s the whole shard, dataloader.py:7-11), and
    the x/y pair is assembled in one C++ pass.  ``backend="auto"`` uses
    it when the toolchain built it; numpy otherwise.  Both backends are
    tested to produce identical batches.
"""

from __future__ import annotations

import os

import numpy as np


def load_tokens(path: str) -> np.ndarray:
    """np.load + widen to int32 (shards are uint16/uint32 on disk)."""
    arr = np.load(path)
    return arr.astype(np.int32)


class ShardedTokenLoader:
    def __init__(
        self,
        B: int,
        T: int,
        data_dir: str,
        split: str = "train",
        process_rank: int = 0,
        num_processes: int = 1,
        master_process: bool = True,
        backend: str = "auto",
    ):
        assert split in {"train", "val"}
        assert backend in {"auto", "native", "numpy"}
        self.B, self.T = B, T
        self.process_rank = process_rank
        self.num_processes = num_processes

        self._backend = backend
        if backend == "numpy":
            self._native = False
        else:
            from mamba_distributed_tpu.data import native

            self._native = native.available()
            if backend == "native" and not self._native:
                raise RuntimeError("native shard reader unavailable")

        shards = sorted(
            os.path.join(data_dir, s)
            for s in os.listdir(data_dir)
            if split in s and s.endswith(".npy")
        )
        assert shards, f"no shards found for split {split} in {data_dir}"
        self.shards = shards
        if master_process:
            backend_name = "native" if self._native else "numpy"
            print(f"found {len(shards)} shards for split {split} ({backend_name})")
        self.reset()

    # --- shard backends ---

    def _open_shard(self, idx: int) -> None:
        path = self.shards[idx]
        if self._native:
            from mamba_distributed_tpu.data.native import NativeShard

            if getattr(self, "_shard", None) is not None:
                self._shard.close()
            try:
                self._shard = NativeShard(path)
            except OSError:
                if self._backend == "native":
                    raise
                # "auto": shard dtype/layout outside the C++ parser's set
                # (e.g. int64, big-endian) — degrade to numpy for this loader
                self._shard = None
                self._native = False
            else:
                self._shard_len = len(self._shard)
                return
        self._shard = None
        self.tokens = load_tokens(path)
        self._shard_len = len(self.tokens)

    def _slice(self, pos: int):
        B, T = self.B, self.T
        if self._native:
            return self._shard.fill_batch(pos, B, T)
        buf = self.tokens[pos : pos + B * T + 1]
        return buf[:-1].reshape(B, T), buf[1:].reshape(B, T)

    # --- reference API ---

    def reset(self) -> None:
        self.current_shard = 0
        self._open_shard(0)
        self.current_position = self.B * self.T * self.process_rank

    def next_batch(self) -> tuple[np.ndarray, np.ndarray]:
        B, T = self.B, self.T
        x, y = self._slice(self.current_position)
        self.current_position += B * T * self.num_processes
        # advance when the *next* strided window would overrun the shard
        # (same guard as reference dataloader.py:46-51 — tails are dropped)
        if self.current_position + (B * T * self.num_processes + 1) > self._shard_len:
            self.current_shard = (self.current_shard + 1) % len(self.shards)
            self._open_shard(self.current_shard)
            self.current_position = B * T * self.process_rank
        return x, y

    # --- exact-resume support (absent from the reference) ---

    def state(self) -> dict:
        return {
            "current_shard": self.current_shard,
            "current_position": self.current_position,
        }

    def restore(self, state: dict) -> None:
        self.current_shard = int(state["current_shard"]) % len(self.shards)
        self._open_shard(self.current_shard)
        self.current_position = int(state["current_position"])
