"""Deterministic synthetic token shards.

The real 10B-token corpus is "bring your own data" (reference README.md:11);
for tests, smoke training, and benchmarks this generates Zipf-distributed
uint16 shards in the same on-disk format the reference loader reads
(``{split}`` in the filename, ``.npy`` of token ids).
"""

from __future__ import annotations

import os

import numpy as np


def ensure_synthetic_shards(
    data_dir: str,
    vocab_size: int = 50257,
    tokens_per_shard: int = 2_097_152,
    num_shards: int = 2,
    val_shards: int = 1,
    seed: int = 1337,
) -> str:
    """Create shards in ``data_dir`` if it doesn't already hold any.

    Zipf-ish marginals give a non-flat unigram distribution so losses move
    the way real text's do (a uniform stream would pin loss at ln(V)).
    """
    if os.path.isdir(data_dir) and any(
        f.endswith(".npy") for f in os.listdir(data_dir)
    ):
        return data_dir
    os.makedirs(data_dir, exist_ok=True)
    for split, count in (("train", num_shards), ("val", val_shards)):
        for i in range(count):
            rng = np.random.default_rng(seed + i + (10_000 if split == "val" else 0))
            # Zipf over a shuffled vocab, clipped into range
            ranks = rng.zipf(1.2, size=tokens_per_shard)
            tokens = (ranks - 1).clip(max=vocab_size - 1).astype(np.uint16)
            path = os.path.join(
                data_dir, f"synthetic_{split}_{i:06d}.npy"
            )
            np.save(path, tokens)
    return data_dir
