"""HellaSwag evaluation, reproducing the reference scoring exactly.

Semantics pinned to /root/reference/eval.py:72-183:
  * each ending tokenized with a leading " " (GPT-2 BPE quirk, eval.py:96-98)
  * rows padded to the per-batch max length, completion mask marks ending
    tokens (eval.py:103-109)
  * autoregressive CE at all positions, logits/tokens/mask shifted by one
    (eval.py:143-155)
  * ``acc`` = argmin of summed loss, ``acc_norm`` = argmin of mean loss
    (eval.py:157-162)
  * evaluation stops at 2,000 examples and appends the summary line
    ``"{n} {correct}/{n} {acc:.4f}"`` (eval.py:180-183) — the comparable
    number to the reference's published 0.324

Fixed relative to the reference: the broken ``Enum`` subclass and dead HF
branch (SURVEY.md §3.4) don't exist here, the tokenizer is injected (this
environment has no network for tiktoken's BPE fetch), and rows are padded
to a bucket so the jitted forward compiles once, not per example.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Iterable, Iterator

import jax
import jax.numpy as jnp
import numpy as np


def render_example(example: dict, encode: Callable[[str], list[int]]):
    """dict -> (data, tokens (4, L) int32, mask (4, L) int32, label)."""
    ctx = example["ctx"]
    label = int(example["label"])
    endings = example["endings"]

    ctx_tokens = encode(ctx)
    data = {"label": label, "ctx_tokens": ctx_tokens, "ending_tokens": []}
    tok_rows, mask_rows = [], []
    for end in endings:
        end_tokens = encode(" " + end)  # " "-prefix rule (reference eval.py:96)
        tok_rows.append(ctx_tokens + end_tokens)
        mask_rows.append([0] * len(ctx_tokens) + [1] * len(end_tokens))
        data["ending_tokens"].append(end_tokens)

    max_len = max(len(r) for r in tok_rows)
    tokens = np.zeros((4, max_len), dtype=np.int32)
    mask = np.zeros((4, max_len), dtype=np.int32)
    for i, (tr, mr) in enumerate(zip(tok_rows, mask_rows)):
        tokens[i, : len(tr)] = tr
        mask[i, : len(mr)] = mr
    return data, tokens, mask, label


def iterate_examples(path: str) -> Iterator[dict]:
    """Yield examples from a local HellaSwag jsonl file.

    The reference downloads from rowanz/hellaswag (eval.py:62-69); this
    environment has no network, so the file must exist locally.
    """
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found. Download hellaswag_val.jsonl from "
            "github.com/rowanz/hellaswag/tree/master/data and point "
            "--data-file at it."
        )
    with open(path) as f:
        for line in f:
            yield json.loads(line)


def _scores_fn(forward):
    """Build the jitted (tokens, mask) -> (sum_loss, avg_loss) scorer.

    Rows are independent: tokens/mask are (R, L) with R = 4 rows per
    example times however many examples the caller packs per call.
    """

    def scores(tokens, mask):
        logits = forward(tokens).astype(jnp.float32)  # (R, L, V)
        shift_logits = logits[:, :-1]
        shift_tokens = tokens[:, 1:]
        logp = jax.nn.log_softmax(shift_logits, axis=-1)
        tok_lp = jnp.take_along_axis(logp, shift_tokens[..., None], axis=-1)[..., 0]
        shift_mask = mask[:, 1:].astype(jnp.float32)
        sum_loss = jnp.sum(-tok_lp * shift_mask, axis=1)
        avg_loss = sum_loss / jnp.maximum(jnp.sum(shift_mask, axis=1), 1.0)
        return sum_loss, avg_loss

    return jax.jit(scores)


def _pad_bucket(n: int, bucket: int = 32) -> int:
    return ((n + bucket - 1) // bucket) * bucket


def evaluate_hellaswag(
    forward: Callable[[jax.Array], jax.Array],
    examples: Iterable[dict],
    encode: Callable[[str], list[int]],
    limit: int = 2000,
    log_path: str | None = None,
    verbose: bool = False,
    bucket: int = 32,
    example_batch: int = 8,
) -> dict:
    """Run the eval; ``forward`` maps (R, L) int32 tokens -> (R, L, V) logits.

    ``example_batch`` examples are packed into one device call (R = 4 x
    example_batch rows) — each row scores independently, so the numbers are
    identical to the reference's one-example-at-a-time loop (eval.py:135),
    just without starving the chip.  Returns {"acc", "acc_norm",
    "num_total", ...} after ``limit`` examples (the reference's
    comparability cap, eval.py:180).
    """
    scorer = _scores_fn(forward)
    num_total = num_correct = num_correct_norm = 0

    def score_batch(batch):
        nonlocal num_total, num_correct, num_correct_norm
        L = _pad_bucket(max(t.shape[1] for _, t, _, _ in batch), bucket)
        pt = np.zeros((4 * example_batch, L), np.int32)  # fixed R: few jit shapes
        pm = np.zeros((4 * example_batch, L), np.int32)
        for i, (_, tokens, mask, _) in enumerate(batch):
            pt[4 * i : 4 * i + 4, : tokens.shape[1]] = tokens
            pm[4 * i : 4 * i + 4, : mask.shape[1]] = mask
        sum_loss, avg_loss = scorer(pt, pm)
        sum_loss = np.asarray(sum_loss).reshape(example_batch, 4)
        avg_loss = np.asarray(avg_loss).reshape(example_batch, 4)
        for i, (_, _, _, label) in enumerate(batch):
            num_total += 1
            num_correct += int(int(np.argmin(sum_loss[i])) == label)
            num_correct_norm += int(int(np.argmin(avg_loss[i])) == label)
            if verbose:
                print(
                    f"{num_total} acc_norm: {num_correct_norm}/{num_total}"
                    f"={num_correct_norm / num_total:.4f}"
                )

    pending = []
    taken = 0
    for example in examples:
        pending.append(render_example(example, encode))
        taken += 1
        if len(pending) == example_batch:
            score_batch(pending)
            pending = []
        if taken == limit:
            break
    if pending:
        score_batch(pending)

    result = {
        "num_total": num_total,
        "acc": num_correct / max(num_total, 1),
        "acc_norm": num_correct_norm / max(num_total, 1),
        "num_correct": num_correct,
        "num_correct_norm": num_correct_norm,
    }
    if log_path:
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        with open(log_path, "a") as f:  # append, like reference eval.py:181
            f.write(
                f"{num_total} {num_correct_norm}/{num_total} "
                f"{num_correct_norm / max(num_total, 1):.4f}"
            )
    return result
