"""Evaluation harnesses (HellaSwag)."""

from mamba_distributed_tpu.eval.hellaswag import (
    evaluate_hellaswag,
    iterate_examples,
    render_example,
)

__all__ = ["evaluate_hellaswag", "iterate_examples", "render_example"]
