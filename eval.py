"""HellaSwag evaluation CLI.

Mirror of the reference's ``python eval.py -m custom|hugging_face ...``
(/root/reference/eval.py:186-200), with its bugs fixed: the reversed
``Enum`` bases that crashed at import and the ``hugging_face`` branch that
never constructed a model (SURVEY.md §3.4) both work here.

  python eval.py -m custom --checkpoint <orbax-dir> --preset mamba2-280m
  python eval.py -m custom --checkpoint model.pt --preset mamba2-280m
  python eval.py -m hugging_face --hf-path <local HF dir>

Needs a GPT-2 BPE tokenizer and a local hellaswag_val.jsonl (a download
the reference does on the fly).  Tokenization is zero-egress: the BPE
algorithm is vendored (mamba_distributed_tpu/data/gpt2_bpe.py) and loads
local encoder.json/vocab.bpe (or HF vocab.json/merges.txt) from
--bpe-dir / $GPT2_BPE_DIR / ./gpt2_bpe, with tiktoken as a fallback.
"""

from __future__ import annotations

import argparse
import enum


class ModelType(str, enum.Enum):  # reference eval.py:22 had the bases reversed
    CUSTOM = "custom"
    HF = "hugging_face"


def get_encoder(bpe_dir: str | None = None):
    from mamba_distributed_tpu.data.gpt2_bpe import load_encoder

    try:
        # vendored zero-egress BPE (local gpt2_bpe/ files), tiktoken fallback
        encode, _ = load_encoder(bpe_dir)
        return encode
    except FileNotFoundError as e:
        raise SystemExit(
            f"GPT-2 tokenizer unavailable: {e}\n(Or inject your own encode "
            "via the library API mamba_distributed_tpu.eval.evaluate_hellaswag.)"
        )


def load_custom(checkpoint: str, preset: str):
    from mamba_distributed_tpu.config import get_preset

    cfg = get_preset(preset).model
    if checkpoint.endswith(".pt"):
        from mamba_distributed_tpu.models.hf import load_hf_checkpoint

        params, cfg = load_hf_checkpoint(checkpoint, cfg)
    else:
        from mamba_distributed_tpu.training.checkpoint import restore_params_only

        params = restore_params_only(checkpoint)
        got = tuple(params["embedding"].shape)
        want = (cfg.vocab_size_padded, cfg.d_model)
        if got != want:
            raise SystemExit(
                f"checkpoint/preset mismatch: embedding {got} in "
                f"{checkpoint!r} but --preset {preset!r} expects {want} — "
                f"pass the preset the checkpoint was trained with"
            )
    return params, cfg


def load_hf(path: str):
    from mamba_distributed_tpu.models.hf import load_hf_checkpoint

    return load_hf_checkpoint(path)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-m", "--model_type", default="custom",
                   choices=[m.value for m in ModelType])
    p.add_argument("--checkpoint", default="log/checkpoint")
    p.add_argument("--preset", default="mamba2-280m")
    p.add_argument("-v", "--hf-path", default=None,
                   help="local HF directory (config.json + pytorch_model.bin)")
    p.add_argument("--data-file", default="hellaswag/hellaswag_val.jsonl")
    p.add_argument("--limit", type=int, default=2000)
    p.add_argument("--example-batch", type=int, default=8,
                   help="examples packed per device call (scores unchanged)")
    p.add_argument("--log-file", default="log/hellaswag_eval.txt")
    p.add_argument("--bpe-dir", default=None,
                   help="dir with GPT-2 encoder.json/vocab.bpe (or HF "
                   "vocab.json/merges.txt); default $GPT2_BPE_DIR or "
                   "./gpt2_bpe, falling back to tiktoken")
    args = p.parse_args()

    from mamba_distributed_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    from mamba_distributed_tpu.eval import evaluate_hellaswag, iterate_examples
    from mamba_distributed_tpu.models import lm_forward

    if args.model_type == ModelType.HF.value:
        assert args.hf_path, "--hf-path required for hugging_face"
        params, cfg = load_hf(args.hf_path)
    else:
        params, cfg = load_custom(args.checkpoint, args.preset)

    result = evaluate_hellaswag(
        lambda tokens: lm_forward(params, cfg, tokens),
        iterate_examples(args.data_file),
        get_encoder(args.bpe_dir),
        limit=args.limit,
        log_path=args.log_file,
        verbose=True,
        example_batch=args.example_batch,
    )
    print(result)


if __name__ == "__main__":
    main()
